"""Sharded-replica MoE serving (ROADMAP item 1): the fleet stops being
single-chip.

The contract under test: a serve replica is a **tp×ep gang** sharing ONE
engine through a ``("tp", "ep")`` mesh — tp shards weights and the paged
pools' kv-head axis (PR 6), ep places MoE expert weights one group per
shard and routes decode tokens through the ``moe.apply_sharded``
all_to_all dispatch inside every fused step — and a request's greedy
stream is IDENTICAL to the single-chip dense-dispatch path at every
width (the serving dispatch is dropless by construction: capacity = the
per-shard token count, so no masked garbage row can evict a real
token's slot). The draft pool of speculative decoding shards with the
same rules (closing PR 8's single-chip note), and a sharded replica's
mid-stream preemption still hands off token-identically through the
existing inflight seam.

Tier-1 keeps the cheap spine (one ep-identity pin + host-side
validation/accounting); the tp×ep matrix and the fleet legs are
``slow`` (tier-1 sits at ~800 s of its 870 s budget).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpu_task.ml.models import transformer
from tpu_task.ml.parallel.mesh import make_mesh
from tpu_task.ml.serving import ServingConfig, ServingEngine
from tpu_task.ml.serving.model import serving_moe_fn

pytestmark = pytest.mark.moe

# Layer 1's FFN is a 4-expert MoE; kv_heads=2 bounds tp at 2 here (the
# wider-tp points build their own config).
MOE = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32, n_kv_heads=2, moe_every=2, n_experts=4)

BASE = ServingConfig(slots=3, block_size=4, n_blocks=32, max_len=32,
                     prefill_buckets=(8, 16))


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), MOE)


def _workload(seed=0, n=3):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, MOE.vocab_size, size=plen), new)
            for plen, new in [(5, 6), (8, 3), (12, 9), (3, 12)][:n]]


def _drain(params, cfg, scfg, mesh=None, temps=None, seed=0, n=3,
           **engine_kw):
    engine = ServingEngine(params, cfg, scfg, mesh=mesh,
                           rng=jax.random.PRNGKey(42), **engine_kw)
    rids = []
    for i, (prompt, new) in enumerate(_workload(seed, n)):
        t = 0.0 if temps is None else temps[i]
        rids.append(engine.submit(
            prompt, new, temperature=t, top_p=0.9 if t > 0 else None))
    out = engine.drain()
    assert engine.allocator.referenced == 0
    return [out[r] for r in rids], engine


# -- resolution + validation (host-side, cheap) -------------------------------


def test_serving_moe_fn_resolution():
    """The dispatch builder's contract: None wherever there is nothing
    to dispatch over (dense config, no mesh, ep=1 — the dense-dispatch
    reference path), a callable on an ep mesh, and a LOUD error for an
    indivisible expert count at construction, never mid-decode."""
    dense = dataclasses.replace(MOE, moe_every=0, n_experts=0)
    mesh = make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    assert serving_moe_fn(MOE, None) is None
    assert serving_moe_fn(dense, mesh) is None
    assert serving_moe_fn(
        MOE, make_mesh(2, axis_names=("tp",), axis_sizes=(2,))) is None
    assert serving_moe_fn(MOE, mesh) is not None
    bad = dataclasses.replace(MOE, n_experts=6)
    with pytest.raises(ValueError, match="n_experts"):
        serving_moe_fn(bad, mesh)


def test_engine_mesh_validation(params):
    """An ep mesh under a dense model is a configuration error (nothing
    shards over ep), as is an expert count the ep width cannot split."""
    dense_cfg = dataclasses.replace(MOE, moe_every=0, n_experts=0)
    dense_params = transformer.init(jax.random.PRNGKey(0), dense_cfg)
    mesh = make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    with pytest.raises(ValueError, match="no MoE layers"):
        ServingEngine(dense_params, dense_cfg, BASE, mesh=mesh)
    bad = dataclasses.replace(MOE, n_experts=6)
    with pytest.raises(ValueError, match="n_experts"):
        ServingEngine(transformer.init(jax.random.PRNGKey(0), bad), bad,
                      BASE, mesh=mesh)


def test_moe_flop_model_top_k_aware():
    """The MFU satellite: the static FLOP model charges ``moe_top_k``
    experts' FFN per token — the top1→top2 delta is exactly one more
    expert's (w_in + w_out) matmul FLOPs per MoE layer, and XLA's own
    count for the dispatched program sits at or above the model (the
    dense dispatch computes every expert's buffer; the model charges
    the algorithmic top-k — the MFU convention)."""
    from tpu_task.obs.goodput import (
        decode_step_cost_analysis_flops,
        token_flops,
    )

    top1 = token_flops(MOE, 1)
    top2 = token_flops(dataclasses.replace(MOE, moe_top_k=2), 1)
    # One MoE layer; one more expert = 2 FLOPs × (d_model·d_ff × 2 mats).
    assert top2 - top1 == 2.0 * 2 * MOE.d_model * MOE.d_ff
    scfg = dataclasses.replace(BASE, prefix_cache=False)
    xla = decode_step_cost_analysis_flops(MOE, scfg)
    if xla is not None:
        assert xla >= scfg.slots * top1 * 0.9


def test_moe_flop_model_cross_check_under_ep_sharding():
    """The ep-sharded fused step lowers and cost-analyzes too: the
    per-shard count is positive and below the single-chip dispatch's
    (each shard holds 1/ep of the experts; the all_to_all moves bytes,
    not FLOPs)."""
    from tpu_task.obs.goodput import decode_step_cost_analysis_flops

    scfg = dataclasses.replace(BASE, prefix_cache=False)
    mesh = make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    sharded = decode_step_cost_analysis_flops(MOE, scfg, mesh=mesh)
    single = decode_step_cost_analysis_flops(MOE, scfg)
    if sharded is None or single is None:
        pytest.skip("backend exposes no cost analysis for this program")
    assert 0 < sharded <= single


# -- the tentpole pin: ep dispatch ≡ single-chip dense ------------------------


@pytest.mark.perf
def test_engine_ep4_moe_greedy_matches_single_chip_dense(params):
    """THE sharded-MoE serving contract (docs/parity.md): an ep=4 engine
    — expert weights one group per shard, every fused step routing
    tokens through the all_to_all dispatch — produces greedy streams
    IDENTICAL to the single-chip engine's dense-dispatch reference, and
    the expert weights really shard (1/ep of the bytes per device)."""
    single, _ = _drain(params, MOE, BASE)
    mesh = make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    sharded, eng = _drain(params, MOE, BASE, mesh=mesh)
    assert single == sharded
    assert eng.stats()["ep"] == 4 and eng.stats()["tp"] == 1
    w_in = eng.params["layers"][1]["w_in"]
    assert w_in.addressable_shards[0].data.nbytes * 4 == w_in.nbytes
    # Dense layers' weights replicate over ep (nothing of theirs is
    # expert-sharded) — the ep axis pays only for what it shards.
    w_gate = eng.params["layers"][0]["w_gate"]
    assert w_gate.addressable_shards[0].data.nbytes == w_gate.nbytes


# -- the slow matrix ----------------------------------------------------------


@pytest.mark.slow
def test_engine_tp_ep_matrix_streams_identical(params):
    """tp×ep composition: {tp2×ep2, tp2×ep4} greedy streams identical
    to single-chip, KV pools still 1/tp per shard, expert weights 1/ep."""
    single, _ = _drain(params, MOE, BASE)
    for tp, ep in ((2, 2), (2, 4)):
        mesh = make_mesh(tp * ep, axis_names=("tp", "ep"),
                         axis_sizes=(tp, ep))
        got, eng = _drain(params, MOE, BASE, mesh=mesh)
        assert got == single, f"streams diverged at tp{tp}xep{ep}"
        k0 = eng.pools[0]["k"]
        assert k0.addressable_shards[0].data.nbytes * tp == k0.nbytes
        w_in = eng.params["layers"][1]["w_in"]
        assert w_in.addressable_shards[0].data.nbytes * tp * ep \
            == w_in.nbytes  # ep over groups × tp over the hidden dim


@pytest.mark.slow
def test_engine_ep_sampled_streams_key_identical(params):
    """Sampled requests: the ep dispatch changes no draw — streams are
    key-identical to single-chip at temperature > 0 (fold_in keys plus
    bit-identical greedy logits would already imply it; this pins the
    sampled program end to end)."""
    temps = [0.9, 0.0, 0.7]
    single, _ = _drain(params, MOE, BASE, temps=temps)
    mesh = make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    sharded, _ = _drain(params, MOE, BASE, mesh=mesh, temps=temps)
    assert single == sharded


@pytest.mark.slow
def test_engine_ep_micro_k_streams_identical(params):
    """micro_k > 1 under ep: the K-wide fused micro-step (the scan body
    runs the all_to_all dispatch K times in one program) stays
    bit-identical to K=1 and to single-chip."""
    scfg = dataclasses.replace(BASE, micro_k=4)
    single, _ = _drain(params, MOE, BASE)
    mesh = make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    got, eng = _drain(params, MOE, scfg, mesh=mesh)
    assert got == single
    assert eng.micro_steps > 0


@pytest.mark.slow
def test_spec_decode_sharded_draft_bit_identical(params):
    """PR 8's "spec decode is single-chip" note closes: a tp=2 engine
    with speculative decoding — draft pool kv-head-sharded with the SAME
    rules as the target's — produces greedy streams bit-identical to
    the non-speculative engine at every width."""
    tp_cfg = dataclasses.replace(MOE, moe_every=0, n_experts=0)
    tp_params = transformer.init(jax.random.PRNGKey(0), tp_cfg)
    draft_cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32, n_kv_heads=2)
    draft_params = transformer.init(jax.random.PRNGKey(7), draft_cfg)
    spec = dataclasses.replace(BASE, spec_k=3)
    mesh = make_mesh(2, axis_names=("tp",), axis_sizes=(2,))

    nonspec, _ = _drain(tp_params, tp_cfg, BASE)
    sharded_spec, eng = _drain(tp_params, tp_cfg, spec, mesh=mesh,
                               draft_params=draft_params,
                               draft_cfg=draft_cfg)
    assert sharded_spec == nonspec
    assert eng.stats()["spec"]["rounds"] > 0
    k0 = eng._draft_pools[0]["k"]
    assert k0.addressable_shards[0].data.nbytes * 2 == k0.nbytes


@pytest.mark.slow
def test_spec_decode_on_moe_target_under_ep(params):
    """Speculative decoding COMPOSES with the ep dispatch: an MoE target
    at ep=2 (spec scoring runs the all_to_all at width k+1) with a dense
    draft stays bit-identical to the non-speculative single-chip path."""
    draft_cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32, n_kv_heads=2)
    draft_params = transformer.init(jax.random.PRNGKey(7), draft_cfg)
    spec = dataclasses.replace(BASE, spec_k=2)
    mesh = make_mesh(2, axis_names=("ep",), axis_sizes=(2,))
    nonspec, _ = _drain(params, MOE, BASE)
    got, eng = _drain(params, MOE, spec, mesh=mesh,
                      draft_params=draft_params, draft_cfg=draft_cfg)
    assert got == nonspec
    assert eng.stats()["ep"] == 2 and eng.stats()["spec"]["rounds"] > 0


@pytest.mark.slow
def test_engine_ep4_serves_experts_exceeding_single_chip_budget():
    """The capacity half of the exit criterion, engine-level: an expert
    table bigger than one chip's (notional) weight budget serves at
    ep=4 with each device holding exactly 1/4 of it."""
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4, d_head=16,
        d_ff=512, dtype=jnp.float32, n_kv_heads=4, moe_every=2,
        n_experts=8)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    expert_bytes = sum(
        int(np.prod(layer[name].shape)) * 4
        for layer in params["layers"] if "w_in" in layer
        for name in ("w_in", "w_out"))
    budget = 1 * 1024 * 1024          # notional per-chip expert budget
    assert expert_bytes > budget                   # won't fit one chip
    assert expert_bytes // 4 <= budget             # fits at ep=4
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=16, max_len=16)
    mesh = make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    eng = ServingEngine(params, cfg, scfg, mesh=mesh)
    for layer in eng.params["layers"]:
        if "w_in" in layer:
            assert layer["w_in"].addressable_shards[0].data.nbytes * 4 \
                == layer["w_in"].nbytes
    prompt = np.random.default_rng(1).integers(0, cfg.vocab_size, size=5)
    rid = eng.submit(prompt, 6)
    out = eng.drain()[rid]
    assert len(out) == 6 and all(0 <= t < cfg.vocab_size for t in out)
    assert eng.allocator.referenced == 0
