"""Observability-plane tests (PR 11): the metrics registry (histogram
math, mergeability, the one-export-path contract), the tracer (ring
bounds, header propagation, error events), durable export + the CLI
renderers, engine spans with the zero-overhead obs-off path, and the
scheduler's per-tenant queue-latency surfacing.

The cross-replica trace-continuity pins (re-dispatch after a preemption
shares the trace, token ranges tile exactly once) live with the fleet
scenarios in tests/test_serve_fleet.py.
"""

import json

import numpy as np
import pytest

from tpu_task.obs import (
    TRACE_HEADER,
    Histogram,
    MetricsRegistry,
    Obs,
    Span,
    SpanExporter,
    TraceContext,
    Tracer,
    chrome_trace,
    export_metrics,
    merge_snapshots,
    read_metrics,
    read_spans,
    render_waterfall,
)

pytestmark = pytest.mark.obs


# -- histograms: the shared quantile math -------------------------------------


def test_histogram_quantile_within_one_bucket_of_exact():
    """The satellite-2 contract: bench.py percentiles and live /stats
    percentiles are the same math, and that math agrees with an exact
    percentile of the raw samples to within one (log-spaced) bucket."""
    rng = np.random.default_rng(7)
    samples = rng.lognormal(mean=-4.0, sigma=1.5, size=1000)
    hist = Histogram("lat")
    for x in samples:
        hist.observe(float(x))
    for q in (0.10, 0.50, 0.90, 0.99):
        exact = float(np.percentile(samples, q * 100))
        got = hist.quantile(q)
        assert got / exact <= hist.growth * 1.001
        assert exact / got <= hist.growth * 1.001


def test_bench_pct_is_the_shared_histogram_math():
    """bench.py's percentile helper IS the obs histogram — pinned against
    numpy on a fixed sample to within one bucket (~33% relative at the
    default 8 buckets/decade), so bench numbers and live /stats numbers
    can never drift apart again."""
    from bench import _hist_pct_ms

    rng = np.random.default_rng(20260804)
    samples_s = rng.exponential(0.05, size=400)
    growth = Histogram("x").growth
    for q in (50, 99):
        ours = _hist_pct_ms(samples_s, q)
        exact = float(np.percentile(samples_s * 1e3, q))
        assert ours / exact <= growth * 1.001
        assert exact / ours <= growth * 1.001


def test_histogram_merge_is_bucketwise_add_and_snapshot_roundtrips():
    rng = np.random.default_rng(3)
    samples = rng.exponential(0.01, size=300)
    whole, left, right = Histogram("a"), Histogram("a"), Histogram("a")
    for i, x in enumerate(samples):
        whole.observe(float(x))
        (left if i % 2 else right).observe(float(x))
    left.merge(right)
    assert left.counts == whole.counts
    assert left.count == whole.count and left.max == whole.max
    back = Histogram.from_snapshot(json.loads(
        json.dumps(whole.snapshot())), "a")
    assert back.counts == whole.counts
    assert back.quantile(0.99) == whole.quantile(0.99)
    with pytest.raises(ValueError, match="grids differ"):
        whole.merge(Histogram("b", per_decade=4))


def test_registry_one_name_one_type_and_merge():
    registry = MetricsRegistry()
    registry.counter("requests").inc(3)
    registry.gauge("depth").set(7)
    registry.histogram("lat").observe(0.25)
    registry.gauge_fn("lazy", lambda: 42.0)
    registry.counter_fn("lazy_total", lambda: 5.0)
    with pytest.raises(TypeError, match="already registered"):
        registry.counter("lat")
    snap = registry.snapshot()
    assert snap["requests"] == {"type": "counter", "value": 3}
    assert snap["lazy"]["value"] == 42.0
    assert snap["lazy_total"]["type"] == "counter"
    assert snap["lat"]["count"] == 1
    merged = merge_snapshots([snap, snap])
    assert merged["requests"]["value"] == 6      # counters add
    assert merged["depth"]["value"] == 7         # gauges last-write
    assert merged["lazy_total"]["value"] == 10   # lazy counters add too
    assert merged["lat"]["count"] == 2           # histograms bucket-add


# -- tracer -------------------------------------------------------------------


def test_tracer_ring_bounds_header_roundtrip_and_error_events():
    tracer = Tracer("unit", capacity=8)
    root = tracer.start("request", fid=1)
    child = tracer.start("dispatch", parent=root, replica="r0")
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    # The one propagation header round-trips the (trace, parent) pair.
    ctx = TraceContext.from_header(child.ctx.to_header())
    assert ctx == child.ctx
    assert TraceContext.from_header(None) is None
    assert TraceContext.from_header("garbage") is None
    tracer.end(child)
    tracer.end(root)
    err = tracer.error("boom", ValueError("bad block"), parent=root)
    assert err.status == "error"
    assert err.attrs["exc_type"] == "ValueError"
    assert err.attrs["error"] == "bad block"
    for _ in range(20):                          # ring drops oldest, never grows
        tracer.event("tick")
    assert len(tracer.finished()) == 8 and tracer.dropped > 0
    drained = tracer.drain()
    assert len(drained) == 8 and not tracer.finished()


def test_chrome_trace_is_valid_and_waterfall_renders(tmp_path):
    tracer = Tracer("render")
    with tracer.span("request", fid=0) as root:
        with tracer.span("dispatch", parent=root, replica="r1"):
            pass
    spans = tracer.finished()
    trace = json.loads(json.dumps(chrome_trace(spans)))   # JSON-clean
    assert trace["displayTimeUnit"] == "ms"
    assert len(trace["traceEvents"]) == 2
    for event in trace["traceEvents"]:
        assert event["ph"] == "X"
        assert set(event) >= {"name", "ts", "dur", "pid", "tid", "args"}
        assert event["pid"] == spans[0].trace_id
    text = render_waterfall(spans)
    assert "request" in text and "dispatch" in text and "ms" in text
    assert render_waterfall([]) == "(no spans)"


def test_span_and_metrics_export_roundtrip(tmp_path):
    from tpu_task.storage.backends import open_backend

    backend, _ = open_backend(str(tmp_path))
    tracer = Tracer("exp")
    tracer.event("gang.placed", tenant="svc", task_id="t-0")
    exporter = SpanExporter(backend)
    key = exporter.export(tracer.drain(), source="scheduler")
    assert key.startswith("obs/spans/scheduler-")
    assert exporter.export([], source="scheduler") is None   # empty = no write
    spans = read_spans(backend)
    assert len(spans) == 1 and spans[0].name == "gang.placed"
    assert spans[0].attrs["tenant"] == "svc"

    registry = MetricsRegistry()
    registry.counter("replica.errors").inc(2)
    registry.histogram("lat").observe(0.5)
    export_metrics(backend, registry.snapshot(), source="r0")
    export_metrics(backend, registry.snapshot(), source="r1")
    merged = read_metrics(backend)
    assert merged["replica.errors"]["value"] == 4
    assert merged["lat"]["count"] == 2


# -- engine spans + the zero-overhead path ------------------------------------


def test_engine_spans_cover_phases_and_off_path_records_nothing():
    from tpu_task.serve.replica import build_engine

    obs = Obs.create("eng")
    tracer = Tracer("caller")
    root = tracer.start("request", fid=0)
    engine = build_engine("micro", obs=obs)
    rid = engine.submit([1, 2, 3, 4], 6, trace=root.ctx)
    tokens = engine.drain()[rid]
    names = [span.name for span in obs.tracer.finished()]
    assert names == ["engine.queue", "engine.prefill", "engine.decode"]
    decode = obs.tracer.finished()[-1]
    assert decode.trace_id == root.trace_id
    assert decode.parent_id == root.span_id      # header-style parenting
    assert decode.attrs["token_start"] == 0
    assert decode.attrs["token_end"] == 6
    stats = engine.stats()
    assert stats["obs"]["engine.ttft_s"]["count"] == 1
    assert stats["obs"]["engine.step_s"]["count"] == stats["steps"]
    assert stats["obs"]["engine.steps"]["value"] == stats["steps"]
    # Goodput/MFU accounting (PR 12) rides the same obs handle: all 6
    # tokens emitted with zero waste, the wall split sums to the busy
    # time, and the gauges export through the registry.
    goodput = stats["goodput"]
    assert goodput["tokens"]["emitted"] == 6
    assert goodput["ratio"] == 1.0
    assert goodput["dispatches"] > 0
    assert goodput["program_s"] > 0
    assert 0.0 <= goodput["host_gap_frac"] <= 1.0
    assert goodput["mfu"] > 0
    assert stats["obs"]["goodput.tokens_emitted"]["value"] == 6
    assert stats["obs"]["goodput.ratio"]["value"] == 1.0

    # obs=None: identical stream, no obs section, no span machinery —
    # the documented zero-overhead path.
    off = build_engine("micro")
    rid_off = off.submit([1, 2, 3, 4], 6)
    assert off.drain()[rid_off] == tokens
    assert off._obs is None and not off._phase_spans
    assert off._goodput is None
    assert "obs" not in off.stats() and "goodput" not in off.stats()


def test_engine_export_closes_spans_as_exported():
    """Drain/export is part of the waterfall: an in-flight request's open
    phase span ends with status=exported and the token range it covered
    — what links the preempted replica's half of a stream to the
    sibling's continuation."""
    from tpu_task.serve.replica import build_engine

    obs = Obs.create("eng2")
    engine = build_engine("micro", obs=obs)
    rid = engine.submit([5, 6, 7], 8)
    for _ in range(4):
        engine.step()
    records = engine.export_inflight()
    assert records and records[0]["rid"] == rid
    exported = [span for span in obs.tracer.finished()
                if span.status == "exported"]
    assert len(exported) == 1
    assert exported[0].attrs["token_end"] == len(records[0]["tokens"])


# -- scheduler queue-latency surfacing (satellite 3) --------------------------


def _virtual_scheduler(tmp_path=None):
    from tpu_task.scheduler import CapacityPool, GangScheduler, TenantQuota
    from tpu_task.scheduler.driver import SimGangDriver

    now = [0.0]
    clock = lambda: now[0]  # noqa: E731
    scheduler = GangScheduler(
        CapacityPool([8]),
        {"svc": TenantQuota(chips=8), "lab": TenantQuota(chips=8)},
        SimGangDriver(clock=clock),
        remote=None if tmp_path is None else str(tmp_path),
        clock=clock)
    return scheduler, now


def test_scheduler_status_has_per_tenant_queue_latency(tmp_path):
    scheduler, now = _virtual_scheduler(tmp_path / "sched")
    scheduler.submit("svc", "v4-8", work=5.0, task_id="a")
    now[0] = 2.0
    scheduler.submit("svc", "v4-8", work=5.0, task_id="b")
    scheduler.tick()                     # both place at t=2
    status = scheduler.status()
    latency = status["tenants"]["svc"]["queue_latency"]
    assert latency["count"] == 2
    # Samples are 2.0s (task a) and ~0s (task b): p99 within one bucket
    # of 2.0, and the mergeable histogram snapshot rides along.
    assert 2.0 / Histogram("x").growth <= latency["p99_s"] <= 2.01
    assert latency["hist"]["count"] == 2
    assert status["tenants"]["lab"]["queue_latency"]["count"] == 0
    # Lifecycle events landed on the gang traces and were already drained
    # into the durable backend by the tick's status persist.
    backend = scheduler.queue._backend
    exported = {span.name for span in read_spans(backend)}
    assert {"gang.submitted", "gang.placed"} <= exported
    assert "sched.queue_latency_s.svc" in read_metrics(backend)


def test_cli_sched_status_renders_queue_latency_columns(tmp_path, capsys):
    from tpu_task.cli.main import main as cli_main

    remote = str(tmp_path / "sched")
    scheduler, now = _virtual_scheduler(tmp_path / "sched")
    scheduler.submit("svc", "v4-8", work=5.0)
    scheduler.tick()
    assert cli_main(["sched", "status", "--remote", remote]) == 0
    out = capsys.readouterr().out
    header = out.splitlines()[0].split()
    assert "QLAT-P50" in header and "QLAT-P99" in header
    svc_row = next(line.split() for line in out.splitlines()[1:]
                   if line.startswith("svc"))
    assert svc_row[header.index("QLAT-P50")].endswith("s")
    # The idle tenant renders a placeholder, not a bogus zero.
    lab_row = next(line.split() for line in out.splitlines()[1:]
                   if line.startswith("lab"))
    assert lab_row[header.index("QLAT-P99")] == "-"


# -- CLI obs trace / top ------------------------------------------------------


def _seeded_backend(tmp_path):
    from tpu_task.storage.backends import open_backend

    backend, _ = open_backend(str(tmp_path))
    tracer = Tracer("router")
    root = tracer.start("request", fid=3)
    dispatch = tracer.start("dispatch", parent=root, fid=3, replica="r0",
                            token_start=0)
    tracer.end(dispatch, token_end=8)
    tracer.end(root)
    SpanExporter(backend).export(tracer.drain(), source="router")
    registry = MetricsRegistry()
    registry.histogram("router.ttft_s").observe(0.05)
    export_metrics(backend, registry.snapshot(), source="router")
    return root.trace_id


def test_cli_obs_trace_waterfall_and_chrome_export(tmp_path, capsys):
    from tpu_task.cli.main import main as cli_main

    trace_id = _seeded_backend(tmp_path)
    chrome_path = str(tmp_path / "trace.json")
    assert cli_main(["obs", "trace", "3", "--remote", str(tmp_path),
                     "--chrome", chrome_path]) == 0
    out = capsys.readouterr().out
    assert trace_id in out and "dispatch" in out
    trace = json.load(open(chrome_path))
    assert {event["name"] for event in trace["traceEvents"]} == \
        {"request", "dispatch"}
    # Unknown id: helpful failure, not a stack trace.
    assert cli_main(["obs", "trace", "nope", "--remote",
                     str(tmp_path)]) == 1
    assert "no trace matching" in capsys.readouterr().out


def test_cli_obs_top_merges_and_renders(tmp_path, capsys):
    from tpu_task.cli.main import main as cli_main

    _seeded_backend(tmp_path)
    assert cli_main(["obs", "top", "--remote", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "router.ttft_s" in out and "P99" in out
    assert cli_main(["obs", "top", "--remote",
                     str(tmp_path / "empty")]) == 1


# -- bench overhead leg -------------------------------------------------------


@pytest.mark.slow
def test_bench_obs_overhead_leg_smoke():
    """The `bench.py obs` section runs end to end: identical streams,
    spans recorded, and a finite overhead number (the ≤ 5% contract is
    asserted on the quiet-box captures, not under pytest load)."""
    from bench import bench_obs

    result = bench_obs(n_requests=3, max_new=6, repeats=2)
    assert result["streams_identical"] is True
    assert result["spans_recorded"] > 0
    assert isinstance(result["overhead_pct"], float)
    assert result["tokens_per_s_obs_on"] > 0
