"""Transformer + MNIST model unit tests (CPU, tiny shapes)."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from tpu_task.ml import checkpoint as ckpt
from tpu_task.ml import train
from tpu_task.ml.models import mnist, transformer

TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32,
)


def test_transformer_shapes():
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, TINY.vocab_size)
    logits = transformer.apply(params, TINY, tokens)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert logits.dtype == jnp.float32


def test_transformer_causality():
    """Future tokens must not influence past logits."""
    params = transformer.init(jax.random.PRNGKey(0), TINY)
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, TINY.vocab_size)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % TINY.vocab_size)
    l1 = transformer.apply(params, TINY, t1)
    l2 = transformer.apply(params, TINY, t2)
    assert jnp.allclose(l1[:, :-1], l2[:, :-1], atol=1e-5)
    assert not jnp.allclose(l1[:, -1], l2[:, -1], atol=1e-5)


def test_train_step_reduces_loss():
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    step = train.make_train_step(TINY, donate=False)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, TINY.vocab_size)
    state, first = step(state, tokens)
    for _ in range(10):
        state, metrics = step(state, tokens)
    assert metrics["loss"] < first["loss"]
    assert int(state.step) == 11


def test_mnist_learns():
    x, y = mnist.synthetic_mnist(jax.random.PRNGKey(0), n=512)
    params = mnist.init_mlp(jax.random.PRNGKey(1))
    grad = jax.jit(jax.grad(mnist.loss_fn))
    for _ in range(40):
        g = grad(params, x, y)
        params = jax.tree.map(lambda p, g: p - 0.1 * g, params, g)
    assert mnist.accuracy(params, x, y) > 0.9


def test_checkpoint_roundtrip(tmp_path):
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    ckpt.save_checkpoint(tmp_path, 3, state)
    ckpt.save_checkpoint(tmp_path, 7, state)
    assert ckpt.latest_step(tmp_path) == 7
    template = jax.tree.map(jnp.zeros_like, state)
    restored = ckpt.restore_checkpoint(tmp_path, template)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        assert jnp.allclose(a, b)


def test_checkpoint_latest_survives_missing_pointer(tmp_path):
    state = {"w": jnp.ones((3,))}
    ckpt.save_checkpoint(tmp_path, 5, state)
    (tmp_path / "LATEST").unlink()
    assert ckpt.latest_step(tmp_path) == 5


def test_embed_backward_chunked_matches_einsum(monkeypatch):
    """The chunked table-gradient path (large tokens×vocab) is exact."""
    from tpu_task.ml.models import transformer as tr

    table = jax.random.normal(jax.random.PRNGKey(0), (64, 16))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 37), 0, 64)
    g = jax.random.normal(jax.random.PRNGKey(2), (4, 37, 16))

    def loss(table):
        return (tr.embed_lookup(table, tokens) * g).sum()

    ref = jax.grad(loss)(table)
    # Force the chunked path (chunk of 256 tokens, 148 tokens padded in).
    monkeypatch.setattr(tr, "_EMBED_ONEHOT_BYTES_LIMIT", 1)
    chunked = jax.grad(loss)(table)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(ref),
                               atol=1e-5)


def test_fused_xent_matches_reference():
    """Blockwise cross-entropy (bounded logits memory for long-context)
    equals the monolithic path exactly — loss and all gradients."""
    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=32, n_layers=2, n_heads=4, d_head=8,
        d_ff=64, dtype=jnp.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 8192)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=False))(params)
    fused_loss, fused_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=True))(params)
    assert abs(float(ref_loss) - float(fused_loss)) < 1e-5
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(fused_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_fused_xent_multiblock_carry_matches_reference(monkeypatch):
    """The MULTI-block path — cross-block m/l renormalization and the
    in-block target gather — must stay exact. The auto-sizer picks a
    whole-vocab single step at these tiny hermetic shapes, so pin the tile
    budget down to force several scan steps (the code path long-context
    production runs)."""
    # 64 tokens x 4 B -> blocks of 4096: vocab 8192 = 2 scan steps; the
    # floor keeps it >1 even if the floor constant changes.
    monkeypatch.setenv("TPU_TASK_XENT_TILE_BYTES", str(64 * 4 * 4096))
    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=32, n_layers=2, n_heads=4, d_head=8,
        d_ff=64, dtype=jnp.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 8192)
    from tpu_task.ml.models.transformer import _auto_xent_block

    assert _auto_xent_block(64, 8192) < 8192  # really multi-block
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=False))(params)
    fused_loss, fused_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=True))(params)
    assert abs(float(ref_loss) - float(fused_loss)) < 1e-5
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(fused_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_fused_xent_nondivisible_vocab_padded_exactly():
    """A vocab not divisible by the block is padded with masked columns —
    the fused result stays exact (no silent fallback that would
    rematerialize full logits for Llama/GPT-style vocab sizes)."""
    cfg = transformer.TransformerConfig(
        vocab_size=100, d_model=16, n_layers=1, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 9), 0, 100)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=False))(params)
    fused_loss, fused_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=True))(params)
    assert abs(float(ref_loss) - float(fused_loss)) < 1e-6
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(fused_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fused_xent_bf16_stays_close_to_f32_reference():
    """The shipped bf16 config: the fused backward accumulates in f32, so
    gradients track the monolithic path at bf16-appropriate tolerance."""
    cfg = transformer.TransformerConfig(
        vocab_size=8192, d_model=32, n_layers=2, n_heads=4, d_head=8,
        d_ff=64, dtype=jnp.bfloat16)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, 8192)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=False))(params)
    fused_loss, fused_grads = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, cfg, tokens, fused=True))(params)
    assert abs(float(ref_loss) - float(fused_loss)) < 2e-3
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(fused_grads)):
        a = np.asarray(a, dtype=np.float32)
        b = np.asarray(b, dtype=np.float32)
        scale = np.abs(b).max() + 1e-9
        assert np.abs(a - b).max() <= 0.03 * scale


def test_profiler_trace_writes_capture_files(tmp_path, monkeypatch):
    """profiling.trace captures a real XLA trace under an explicit dir (the
    workdir sync loop exports it); step_window gates on the step index; the
    default (no dir) is env-gated: no-op when TPU_TASK_PROFILE is unset,
    traced into the env dir when set."""
    from tpu_task.ml import profiling

    log_dir = tmp_path / "profiles"
    with profiling.trace(str(log_dir)):
        with profiling.annotate("unit-span"):
            jax.jit(lambda x: x * 2)(jnp.ones((8, 8))).block_until_ready()
    captured = [p for p in log_dir.rglob("*") if p.is_file()]
    assert captured, "no trace files written"

    # Window gating: outside [start, stop) nothing is captured.
    with profiling.step_window(5, start=10, stop=12,
                               log_dir=str(tmp_path / "none")):
        pass
    assert not (tmp_path / "none").exists()

    # Env-gated default: unset -> no-op, nothing touches the filesystem.
    monkeypatch.delenv("TPU_TASK_PROFILE", raising=False)
    monkeypatch.chdir(tmp_path)  # any stray relative writes would land here
    before = sorted(p.name for p in tmp_path.iterdir())
    with profiling.trace():
        pass
    assert sorted(p.name for p in tmp_path.iterdir()) == before

    # Env-gated default: set -> traced into the env-named directory.
    monkeypatch.setenv("TPU_TASK_PROFILE", str(tmp_path / "profiles-env"))
    with profiling.trace():
        jax.jit(lambda x: x + 1)(jnp.ones((4,))).block_until_ready()
    assert [p for p in (tmp_path / "profiles-env").rglob("*") if p.is_file()]


def test_checkpoint_keep_retains_newest_n(tmp_path):
    """keep=N prunes older checkpoints after the pointer update: LATEST
    always survives, restore still works, bucket usage stays bounded."""
    state = {"w": jnp.arange(4.0)}
    for step in (1, 2, 3, 4, 5):
        ckpt.save_checkpoint(tmp_path, step, {"w": jnp.arange(4.0) + step},
                             keep=2)
    names = sorted(p.name for p in tmp_path.glob("ckpt-*.npz"))
    assert names == ["ckpt-4.npz", "ckpt-5.npz"]
    assert ckpt.latest_step(tmp_path) == 5
    restored = ckpt.restore_checkpoint(tmp_path, state)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(4.0) + 5)
    with pytest.raises(ValueError, match="keep"):
        ckpt.save_checkpoint(tmp_path, 6, state, keep=0)

    # Out-of-order re-save (rollback): the just-written step must survive
    # the prune and LATEST must stay consistent with it.
    ckpt.save_checkpoint(tmp_path, 3, {"w": jnp.arange(4.0) + 3}, keep=2)
    assert (tmp_path / "ckpt-3.npz").exists()
    assert ckpt.latest_step(tmp_path) == 3
    rolled = ckpt.restore_checkpoint(tmp_path, state)
    np.testing.assert_allclose(np.asarray(rolled["w"]), np.arange(4.0) + 3)


def test_sharded_checkpoint_keep_prunes_own_shards_and_manifests(tmp_path):
    state = {"w": jnp.arange(8.0)}
    for step in (10, 20, 30):
        ckpt.save_checkpoint_sharded(tmp_path, step, state, keep=2)
    shard_names = sorted(p.name for p in tmp_path.glob("ckpt-*.shard-*.npz"))
    assert shard_names == ["ckpt-20.shard-0.npz", "ckpt-30.shard-0.npz"]
    assert sorted(p.name for p in tmp_path.glob("ckpt-*.meta")) == \
        ["ckpt-20.meta", "ckpt-30.meta"]
    restored = ckpt.restore_checkpoint_sharded(tmp_path, state)
    np.testing.assert_allclose(np.asarray(restored["w"]), np.arange(8.0))

    # keep=1 would leave skew windows with NO complete shard set: rejected.
    with pytest.raises(ValueError, match="keep"):
        ckpt.save_checkpoint_sharded(tmp_path, 40, state, keep=1)


def test_generate_greedy_matches_full_forward_recompute():
    """KV-cache greedy decoding must equal the naive recompute-everything
    loop token-for-token — cache correctness, rope offsets, and masking."""
    from tpu_task.ml.models import decoding

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                TINY.vocab_size)
    out = decoding.generate(params, TINY, prompt, max_new_tokens=6)
    assert out.shape == (2, 6)

    seq = prompt
    for _ in range(6):
        logits = transformer.apply(params, TINY, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
        seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 5:]))


def test_generate_sampling_deterministic_under_fixed_rng():
    from tpu_task.ml.models import decoding

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0,
                                TINY.vocab_size)
    a = decoding.generate(params, TINY, prompt, 5, temperature=0.8,
                          rng=jax.random.PRNGKey(7))
    b = decoding.generate(params, TINY, prompt, 5, temperature=0.8,
                          rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.asarray(a).max()) < TINY.vocab_size
    with pytest.raises(ValueError, match="rng"):
        decoding.generate(params, TINY, prompt, 2, temperature=0.5)


def test_generate_runs_under_jit():
    """The whole generation (prefill + scan) compiles as one program."""
    from tpu_task.ml.models import decoding

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0,
                                TINY.vocab_size)
    jitted = jax.jit(lambda p, t: decoding.generate(p, TINY, t, 3))
    eager = decoding.generate(params, TINY, prompt, 3)
    np.testing.assert_array_equal(np.asarray(jitted(params, prompt)),
                                  np.asarray(eager))


def test_gradient_accumulation_equals_full_batch_step():
    """accum_steps=N must produce the same loss and updated params as the
    single full-batch step: equal-sized microbatches of a token-mean loss
    make mean-of-grads equal grad-of-mean."""
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                                TINY.vocab_size)
    full_state = train.init_state(jax.random.PRNGKey(0), TINY)
    full_step = train.make_train_step(TINY, donate=False)
    full_state, full_metrics = full_step(full_state, tokens)

    acc_state = train.init_state(jax.random.PRNGKey(0), TINY)
    acc_step = train.make_train_step(TINY, donate=False, accum_steps=2)
    acc_state, acc_metrics = acc_step(acc_state, tokens)

    assert abs(float(acc_metrics["loss"]) - float(full_metrics["loss"])) < 1e-6
    assert abs(float(acc_metrics["grad_norm"])
               - float(full_metrics["grad_norm"])) < 1e-5
    for a, b in zip(jax.tree.leaves(acc_state.params),
                    jax.tree.leaves(full_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    with pytest.raises(ValueError, match="divisible"):
        acc_3 = train.make_train_step(TINY, donate=False, accum_steps=3)
        acc_3(train.init_state(jax.random.PRNGKey(0), TINY), tokens)
    with pytest.raises(ValueError, match="accum_steps"):
        train.make_train_step(TINY, accum_steps=0)


GQA = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    n_kv_heads=2, dtype=jnp.float32)


def test_gqa_forward_shapes_and_causality():
    params = transformer.init(jax.random.PRNGKey(0), GQA)
    assert params["layers"][0]["wk"].shape == (32, 2 * 8)  # narrow kv proj
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, GQA.vocab_size)
    logits = transformer.apply(params, GQA, t1)
    assert logits.shape == (1, 16, GQA.vocab_size)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % GQA.vocab_size)
    l2 = transformer.apply(params, GQA, t2)
    assert jnp.allclose(logits[:, :-1], l2[:, :-1], atol=1e-5)


def test_gqa_matches_explicit_kv_expansion():
    """GQA must equal MHA run on the same weights with kv heads explicitly
    repeated — grouping is weight sharing, not different math."""
    params = transformer.init(jax.random.PRNGKey(0), GQA)
    wide = jax.tree.map(lambda x: x, params)
    for layer in wide["layers"]:
        for name in ("wk", "wv"):
            narrow = layer[name].reshape(32, GQA.kv_heads, 8)
            layer[name] = jnp.repeat(narrow, GQA.n_heads // GQA.kv_heads,
                                     axis=1).reshape(32, GQA.d_attn)
    mha_cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
        dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    np.testing.assert_allclose(
        np.asarray(transformer.apply(params, GQA, tokens)),
        np.asarray(transformer.apply(wide, mha_cfg, tokens)), atol=1e-5)


def test_gqa_generate_matches_full_forward_and_shrinks_cache():
    from tpu_task.ml.models import decoding

    params = transformer.init(jax.random.PRNGKey(0), GQA)
    caches = decoding.init_cache(GQA, batch=1, max_len=12)
    assert caches[0]["k"].shape == (1, 12, 2, 8)  # kv heads, not q heads

    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                GQA.vocab_size)
    out = decoding.generate(params, GQA, prompt, max_new_tokens=6)
    seq = prompt
    for _ in range(6):
        logits = transformer.apply(params, GQA, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
        seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 5:]))


def test_gqa_train_step_and_sp_step_run():
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 64)
    state = train.init_state(jax.random.PRNGKey(0), GQA)
    step = train.make_train_step(GQA, donate=False)
    state, first = step(state, tokens)
    for _ in range(5):
        state, metrics = step(state, tokens)
    assert float(metrics["loss"]) < float(first["loss"])

    # Sequence-parallel step under GQA: the expand_kv wiring must equal
    # the plain replicated GQA step exactly.
    from tpu_task.ml.parallel import mesh as meshlib

    plain_state = train.init_state(jax.random.PRNGKey(0), GQA)
    plain_step = train.make_train_step(GQA, donate=False)
    plain_state, plain_metrics = plain_step(plain_state, tokens)
    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    sp_state = train.init_state(jax.random.PRNGKey(0), GQA)
    sp_state, _ = train.shard_state(sp_state, GQA, mesh)
    sp_step = train.make_sp_train_step(GQA, mesh, donate=False)(sp_state)
    sp_state, sp_metrics = sp_step(sp_state, tokens)
    assert abs(float(sp_metrics["loss"])
               - float(plain_metrics["loss"])) < 1e-5

    with pytest.raises(ValueError, match="divisible"):
        transformer.TransformerConfig(n_heads=4, n_kv_heads=3).kv_heads
    with pytest.raises(ValueError, match="n_kv_heads"):
        transformer.TransformerConfig(n_heads=4, n_kv_heads=0).kv_heads


def test_generate_eos_latches_per_row():
    """Once a row emits eos_token, it keeps emitting it; other rows keep
    generating — static shapes, per-row completion."""
    from tpu_task.ml.models import decoding

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                TINY.vocab_size)
    plain = np.asarray(decoding.generate(params, TINY, prompt, 8))
    # Use row 0's third greedy token as the EOS: everything after its first
    # occurrence in row 0 must be EOS; row 1 (different tokens) unaffected
    # until/unless it emits the same token.
    eos = int(plain[0, 2])
    out = np.asarray(decoding.generate(params, TINY, prompt, 8,
                                       eos_token=eos))
    first_hit = int(np.argmax(out[0] == eos))
    assert out[0, first_hit] == eos
    assert (out[0, first_hit:] == eos).all()
    np.testing.assert_array_equal(out[0, :first_hit], plain[0, :first_hit])


def test_generate_top_p_restricts_support():
    """top_p sampling only ever emits tokens greedy-plausible under the
    nucleus: with a tiny top_p it degenerates to greedy."""
    from tpu_task.ml.models import decoding

    params = transformer.init(jax.random.PRNGKey(0), TINY)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0,
                                TINY.vocab_size)
    greedy = np.asarray(decoding.generate(params, TINY, prompt, 6))
    nucleus = np.asarray(decoding.generate(
        params, TINY, prompt, 6, temperature=1.0, top_p=1e-6,
        rng=jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(nucleus, greedy)  # nucleus of 1 = argmax
    with pytest.raises(ValueError, match="top_p"):
        decoding.generate(params, TINY, prompt, 2, temperature=1.0,
                          top_p=1.5, rng=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="top_p"):
        decoding.generate(params, TINY, prompt, 2, top_p=0.5)


# -- _top_p_filter edge-case properties (random-logit property tests) --------

def _nucleus_cases(n=64, vocab=40):
    """Random (logits, top_p) pairs spanning peaky and flat distributions."""
    rng = np.random.default_rng(11)
    for i in range(n):
        scale = float(rng.uniform(0.2, 8.0))    # flat → peaky
        logits = rng.standard_normal(vocab) * scale
        top_p = float(rng.uniform(0.05, 1.0))
        yield jnp.asarray(logits, jnp.float32), top_p


def test_top_p_filter_kept_mass_is_at_least_top_p():
    """Property: the surviving tokens always carry >= top_p of the original
    probability mass (the nucleus is the SMALLEST prefix reaching top_p,
    so it reaches it)."""
    from tpu_task.ml.models import decoding

    for logits, top_p in _nucleus_cases():
        kept = np.asarray(decoding._top_p_filter(logits, top_p)) > -1e29
        probs = np.asarray(jax.nn.softmax(logits))
        assert probs[kept].sum() >= top_p - 1e-5, (top_p, probs[kept].sum())


def test_top_p_filter_keeps_at_least_one_token_at_tiny_top_p():
    """Property: even top_p ~ 0 keeps the argmax (its preceding mass is 0),
    and drops everything else when the argmax alone covers top_p."""
    from tpu_task.ml.models import decoding

    for logits, _ in _nucleus_cases(n=16):
        out = np.asarray(decoding._top_p_filter(logits, 1e-9))
        kept = out > -1e29
        assert kept.sum() == 1
        assert kept[int(np.argmax(np.asarray(logits)))]


def test_top_p_filter_threshold_ties_keep_all_tied_tokens():
    """The keep rule is ``logits >= threshold``: tokens exactly tied with
    the nucleus boundary all survive, whichever of them the sort placed
    inside the prefix — no order-dependent coin flip."""
    from tpu_task.ml.models import decoding

    # Two exactly-tied top tokens, each ~49.9% — top_p=0.5 needs one of
    # them, the tie keeps both, the tail token stays dropped.
    logits = jnp.asarray([10.0, 10.0, 0.0], jnp.float32)
    out = np.asarray(decoding._top_p_filter(logits, 0.5))
    assert (out[:2] > -1e29).all() and out[2] < -1e29
    # Four-way tie, top_p small: all four tied maxima survive.
    logits = jnp.asarray([3.0, 3.0, 3.0, 3.0, -1.0], jnp.float32)
    out = np.asarray(decoding._top_p_filter(logits, 0.1))
    assert (out[:4] > -1e29).all() and out[4] < -1e29


def test_top_p_filter_per_row_matches_scalar_rows():
    """(batch,) top_p filters each row exactly as the scalar call would —
    the serving engine samples every slot with its own request's top_p in
    one program."""
    from tpu_task.ml.models import decoding

    rng = np.random.default_rng(12)
    logits = jnp.asarray(rng.standard_normal((5, 32)), jnp.float32)
    tops = [0.1, 0.3, 0.6, 0.9, 1.0]
    batched = np.asarray(decoding._top_p_filter(
        logits, jnp.asarray(tops, jnp.float32)))
    for i, p in enumerate(tops):
        np.testing.assert_array_equal(
            batched[i], np.asarray(decoding._top_p_filter(logits[i], p)))
