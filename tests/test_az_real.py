"""Real-mode Azure backend against scripted ARM transports.

Covers VERDICT r2 row 22: the ARM control plane — resource-group-rooted DAG
(task/az/task.go), VMSS body with CustomData/spot/image grammar
(resource_virtual_machine_scale_set.go:64-235), instance-view aggregation
(:240-301), and storage account + blob container plumbing.
"""

import json

import pytest

from test_http_resilience import FakeSleep, FakeTransport

from tpu_task.common.cloud import AZCredentials, Cloud, Credentials, Provider
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import Environment, Size, Spot, Task as TaskSpec


def _cloud():
    return Cloud(provider=Provider.AZ, region="eastus",
                 credentials=Credentials(az=AZCredentials(
                     client_id="cid", client_secret="cs",
                     subscription_id="sub-1", tenant_id="tid")))


def _ok(payload) -> tuple:
    return ("ok", json.dumps(payload).encode())


def _real_task(spec=None):
    from tpu_task.backends.az.task import AZRealTask

    task = AZRealTask(_cloud(), Identifier.deterministic("azreal"),
                      spec or TaskSpec())
    task.client._token._fetch = lambda: ("tok", 3600.0)
    task.client._sleep = FakeSleep()
    return task


def test_factory_routes_to_real_az_with_credentials(monkeypatch):
    from tpu_task.backends.az.task import AZRealTask, new_az_task

    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    task = new_az_task(_cloud(), Identifier.deterministic("t"), TaskSpec())
    assert isinstance(task, AZRealTask)


def test_factory_stays_hermetic_without_credentials(monkeypatch):
    from tpu_task.backends.az.task import AZTask, new_az_task

    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    task = new_az_task(Cloud(provider=Provider.AZ, region="eastus"),
                       Identifier.deterministic("t"), TaskSpec())
    assert isinstance(task, AZTask)


def test_image_grammar():
    from tpu_task.backends.az.resources import parse_image

    user, reference, plan = parse_image("")
    assert user == "ubuntu"
    assert reference == {"publisher": "Canonical",
                         "offer": "0001-com-ubuntu-server-focal",
                         "sku": "20_04-lts", "version": "latest"}
    user, reference, _ = parse_image("admin@Pub:Off:Sku:1.2.3")
    assert user == "admin" and reference["version"] == "1.2.3"
    with pytest.raises(ValueError, match="image"):
        parse_image("missing-at-sign:x:y:z")


def test_vmss_body_spot_and_disk():
    from tpu_task.backends.az.api import ArmClient
    from tpu_task.backends.az.resources import VirtualMachineScaleSet

    client = ArmClient("sub-1", "tid", "cid", "cs")
    scale_set = VirtualMachineScaleSet(
        client, "tpi-x", "tpi-x", "eastus", vm_size="Standard_F8s_v2",
        subnet_id="/subnets/s1", image_reference={"publisher": "P"},
        ssh_user="ubuntu", ssh_public_key="ssh-rsa AAA",
        custom_data_b64="Q0Q=", spot=0.0, disk_size_gb=150,
        tags={"tpu-task-remote": ":azureblob,account='a':tpi-x"})
    body = scale_set.body()
    assert body["sku"] == {"name": "Standard_F8s_v2", "tier": "Standard",
                           "capacity": 0}
    profile = body["properties"]["virtualMachineProfile"]
    # spot == 0 → Spot priority with no price cap (scale_set.go:219-229).
    assert profile["priority"] == "Spot"
    assert profile["evictionPolicy"] == "Delete"
    assert profile["billingProfile"] == {"maxPrice": -1}
    assert profile["storageProfile"]["osDisk"]["diskSizeGB"] == 150
    assert profile["osProfile"]["customData"] == "Q0Q="
    assert body["tags"]["tpu-task-remote"].startswith(":azureblob")
    # On-demand: no priority key at all.
    scale_set.spot = -1.0
    assert "priority" not in scale_set.body()["properties"][
        "virtualMachineProfile"]


def test_create_issues_full_resource_plan(monkeypatch):
    spec = TaskSpec(size=Size(machine="m"),
                    environment=Environment(script="#!/bin/sh\ntrue"),
                    spot=Spot(-1))
    task = _real_task(spec)
    monkeypatch.setattr("tpu_task.machine.wheel.stage_wheel", lambda remote: "")
    # Container creation goes through the blob data plane — stub it and the
    # key fetch the connection string needs.
    monkeypatch.setattr(
        "tpu_task.backends.az.task.AZRealTask._container",
        lambda self: type("C", (), {
            "create": lambda s: None, "account_key": "KEY",
            "connection_string": lambda s:
                f":azureblob,account='{self.identifier.short()}',key='KEY':"
                f"{self.identifier.long()}"})())
    succeeded = {"properties": {"provisioningState": "Succeeded"}}
    transport = FakeTransport([
        _ok({}),                                    # resource group PUT
        _ok(succeeded),                             # storage account PUT
        _ok(succeeded),                             # storage account wait GET
        _ok({"id": "/nsg-id", **succeeded}),        # NSG PUT
        _ok({"properties": {"subnets": [{"id": "/subnet-id"}],
             "provisioningState": "Succeeded"}}),   # VNet PUT
        ("http", 404),                              # recorded-remote probe
        _ok(succeeded),                             # VMSS PUT
        _ok(succeeded),                             # VMSS wait GET
        _ok({}),                                    # scale PATCH
    ])
    task.client._urlopen = transport
    task.create()

    urls = [r.full_url for r in transport.requests]
    assert "/resourcegroups/" in urls[0]
    assert "storageAccounts" in urls[1]
    assert "networkSecurityGroups" in urls[3]
    assert "virtualNetworks" in urls[4]
    assert "virtualMachineScaleSets" in urls[6]
    vmss_body = json.loads(transport.requests[6].data)
    assert vmss_body["sku"]["capacity"] == 0
    assert vmss_body["properties"]["virtualMachineProfile"][
        "networkProfile"]["networkInterfaceConfigurations"][0][
        "properties"]["ipConfigurations"][0]["properties"][
        "subnet"]["id"] == "/subnet-id"
    # Sanitized record: the account KEY never lands in VMSS tags.
    assert "KEY" not in vmss_body["tags"]["tpu-task-remote"]
    patch_body = json.loads(transport.requests[8].data)
    assert patch_body == {"sku": {"capacity": 1}}


def test_read_aggregates_addresses_status_events(monkeypatch):
    task = _real_task(TaskSpec())
    transport = FakeTransport([
        _ok({"sku": {"capacity": 2}, "tags": {}}),             # VMSS GET
        _ok({"virtualMachine": {"statusesSummary": [
            {"code": "ProvisioningState/succeeded", "count": 2}]},
            "statuses": [{"code": "ProvisioningState/succeeded",
                          "level": "Info", "displayStatus": "OK",
                          "time": "2026-07-29T00:00:00Z"}]}),  # instanceView
        _ok({"value": [{"properties": {"ipAddress": "20.1.2.3"}},
                       {"properties": {"ipAddress": "20.1.2.4"}}]}),  # IPs
    ])
    task.client._urlopen = transport
    monkeypatch.setattr("tpu_task.backends.gcs_remote.storage_status",
                        lambda remote, initial=None: initial)
    monkeypatch.setattr(
        "tpu_task.backends.az.task.AZRealTask._remote",
        lambda self: ":azureblob,account='a',key='k':x")
    task.read()
    from tpu_task.common.values import StatusCode

    assert task.get_addresses() == ["20.1.2.3", "20.1.2.4"]
    assert task.spec.status == {StatusCode.ACTIVE: 2}
    assert task.spec.events[0].code == "ProvisioningState/succeeded"
    assert task.observed_parallelism() == 2


def test_delete_is_resource_group_teardown():
    task = _real_task(TaskSpec())
    transport = FakeTransport([
        ("http", 404),  # recorded-remote probe: VMSS gone
        ("http", 404),  # resource group DELETE: already gone
    ])
    task.client._urlopen = transport
    task._account_key = "K"  # avoid listKeys on the deterministic remote
    task.delete()  # idempotent, no raise
    assert transport.requests[-1].get_method() == "DELETE"
    assert "/resourcegroups/" in transport.requests[-1].full_url


def test_bare_read_recovers_recorded_remote_from_vmss_tags():
    task = _real_task(TaskSpec())
    short = task.identifier.short()
    transport = FakeTransport([
        _ok({"sku": {"capacity": 1},
             "tags": {"tpu-task-remote":
                      f":azureblob,account='{short}':shared-container"}}),
        _ok({"virtualMachine": {}, "statuses": []}),
        _ok({"value": []}),
        _ok({"keys": [{"value": "fetched-key"}]}),  # listKeys re-fetch
    ])
    task.client._urlopen = transport
    remote = task._remote()
    # The sanitized tag gains the key back via listKeys (never stored).
    assert "fetched-key" in remote
    assert remote.endswith(":shared-container")


def test_nsg_rule_semantics():
    """values.py firewall semantics on Azure: None = allow any (explicit
    rule, since Azure denies inbound by default); [] = allow none; egress
    restrictions render an explicit outbound deny."""
    from tpu_task.backends.az.api import ArmClient
    from tpu_task.backends.az.resources import SecurityGroup
    from tpu_task.common.values import Firewall, FirewallRule

    client = ArmClient("sub-1", "tid", "cid", "cs")

    def rules(firewall):
        group = SecurityGroup(client, "rg", "tpi-x", "eastus", firewall)
        return group.body()["properties"]["securityRules"]

    # Default spec: allow-any inbound needs an explicit rule.
    default_rules = rules(Firewall())
    assert len(default_rules) == 1
    assert default_rules[0]["properties"]["destinationPortRange"] == "*"
    assert default_rules[0]["properties"]["direction"] == "Inbound"

    # Ports [22]: one inbound allow; default egress stays Azure-open.
    port_rules = rules(Firewall(ingress=FirewallRule(ports=[22])))
    assert [r["properties"]["destinationPortRange"] for r in port_rules] == ["22"]

    # Allow-none ingress: no rules at all (Azure default deny covers it).
    assert rules(Firewall(ingress=FirewallRule(ports=[]))) == []

    # Restricted egress: allow rules + explicit outbound deny.
    egress_rules = rules(Firewall(egress=FirewallRule(ports=[443])))
    directions = [(r["properties"]["direction"], r["properties"]["access"])
                  for r in egress_rules]
    assert ("Outbound", "Allow") in directions
    assert ("Outbound", "Deny") in directions

    # Multi-net rules must carry sourceAddressPrefixes ALONE — ARM rejects
    # rules that specify both singular and plural source fields.
    multi = rules(Firewall(ingress=FirewallRule(
        ports=[22], nets=["10.0.0.0/8", "192.168.0.0/16"])))
    assert multi[0]["properties"]["sourceAddressPrefixes"] == [
        "10.0.0.0/8", "192.168.0.0/16"]
    assert "sourceAddressPrefix" not in multi[0]["properties"]
    single = rules(Firewall(ingress=FirewallRule(ports=[22],
                                                 nets=["10.0.0.0/8"])))
    assert single[0]["properties"]["sourceAddressPrefix"] == "10.0.0.0/8"
    assert "sourceAddressPrefixes" not in single[0]["properties"]

    # Egress nets with ports=None means every port to those nets
    # (values.py): an any-port Allow must precede the deny-all, or the VM
    # loses ALL outbound traffic.
    any_port = rules(Firewall(egress=FirewallRule(nets=["10.1.0.0/16"])))
    pairs = [(r["properties"]["direction"], r["properties"]["access"],
              r["properties"]["destinationPortRange"]) for r in any_port]
    assert ("Outbound", "Allow", "*") in pairs
    assert ("Outbound", "Deny", "*") in pairs
    allow = next(r for r in any_port
                 if r["properties"]["access"] == "Allow"
                 and r["properties"]["direction"] == "Outbound")
    deny = next(r for r in any_port if r["properties"]["access"] == "Deny")
    assert allow["properties"]["priority"] < deny["properties"]["priority"]
    # Outbound nets constrain the DESTINATION side (the remote end).
    assert allow["properties"]["destinationAddressPrefix"] == "10.1.0.0/16"
    assert allow["properties"]["sourceAddressPrefix"] == "*"

    # Egress allow-none: only the deny outbound (no pointless Allow rules;
    # the default ingress still renders its inbound allow-any).
    none_rules = rules(Firewall(egress=FirewallRule(nets=[])))
    outbound = [(r["properties"]["direction"], r["properties"]["access"])
                for r in none_rules
                if r["properties"]["direction"] == "Outbound"]
    assert outbound == [("Outbound", "Deny")]
