"""HCL parser + declarative apply/refresh/destroy tests (reference:
iterative/resource_task.go lifecycle semantics, cmd/leo/root.go HCL bridge)."""

import json
import time

import pytest

from tpu_task.common.values import StatusCode
from tpu_task.frontend import apply, destroy, load_tasks, refresh
from tpu_task.frontend.declarative import State, build_cloud, build_spec
from tpu_task.frontend.hcl import HclError, parse_hcl

EXAMPLE_TF = '''
# Example mirroring the reference's docs/resources/task.md usage.
resource "iterative_task" "example" {
  cloud       = "tpu"
  region      = "us-central2"
  machine     = "v4-32"
  disk_size   = 50
  spot        = 0
  parallelism = 2
  timeout     = 3600

  environment = { GREETING = "hello", INHERITED = "" }
  tags        = { team = "ml" }

  storage {
    workdir = "."
    output  = "results"
    exclude = ["cache/**"]
  }

  script = <<-END
    #!/bin/bash
    echo "$GREETING world"
  END
}
'''


def test_parse_example():
    root = parse_hcl(EXAMPLE_TF)
    block = root.find("resource")[0]
    assert block.labels == ["iterative_task", "example"]
    assert block.body["machine"] == "v4-32"
    assert block.body["spot"] == 0
    assert block.body["parallelism"] == 2
    assert block.body["environment"] == {"GREETING": "hello", "INHERITED": ""}
    assert block.find("storage")[0].body["output"] == "results"
    script = block.body["script"]
    assert script.startswith("#!/bin/bash")
    assert 'echo "$GREETING world"' in script


def test_parse_errors():
    with pytest.raises(HclError):
        parse_hcl('resource "x" { a = }')
    with pytest.raises(HclError):
        parse_hcl("a = <<EOF\nnever terminated")
    with pytest.raises(HclError):
        parse_hcl("💥")


def test_parse_comments_and_types():
    root = parse_hcl('''
      // line comment
      /* block
         comment */
      a = "str"        # trailing
      b = -3.5
      c = [1, 2, 3]
      d = true
      e = null
    ''')
    assert root.body == {"a": "str", "b": -3.5, "c": [1, 2, 3],
                         "d": True, "e": None}


def test_build_spec_mapping(tmp_path):
    (tmp_path / "main.tf").write_text(EXAMPLE_TF)
    defn = load_tasks(tmp_path)[0]
    cloud = build_cloud(defn)
    assert cloud.provider.value == "tpu"
    assert cloud.tags == {"team": "ml"}
    spec = build_spec(defn)
    assert spec.size.machine == "v4-32"
    assert spec.size.storage == 50
    assert float(spec.spot) == 0.0
    assert spec.parallelism == 2
    assert spec.environment.timeout.total_seconds() == 3600
    assert spec.environment.variables["GREETING"] == "hello"
    assert spec.environment.variables["INHERITED"] is None  # glob/inherit
    assert spec.environment.variables["TPI_TASK"] == "true"
    assert "CI_*" in spec.environment.variables
    assert spec.environment.directory_out == "results"
    assert spec.environment.exclude_list == ["cache/**"]
    assert spec.firewall.ingress.ports == [22, 80]


LOCAL_TF = '''
resource "iterative_task" "demo" {
  cloud   = "local"
  name    = "frontend-demo"
  timeout = 300
  storage {
    workdir = "work"
    output  = "output"
  }
  script = <<-END
    #!/bin/bash
    cat input.txt
    mkdir -p output && echo done > output/result.txt
  END
}
'''


@pytest.fixture
def config_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_TASK_LOCAL_ROOT", str(tmp_path / "control-plane"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    config = tmp_path / "config"
    work = config / "work"
    work.mkdir(parents=True)
    (config / "main.tf").write_text(LOCAL_TF)
    (work / "input.txt").write_text("tf-payload")
    return config


def wait_status(config_dir, name, code, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        outputs = refresh(config_dir)[name]
        if outputs["status"].get(code.value, 0) >= 1:
            return outputs
        time.sleep(0.2)
    raise AssertionError(f"status {code} not reached: {outputs}")


def test_apply_refresh_destroy_lifecycle(config_dir):
    results = apply(config_dir)
    assert "demo" in results

    state = State(config_dir)
    identifier = state.identifier("demo")
    assert identifier and identifier.startswith("tpi-frontend-demo-")

    # apply is idempotent: same identifier, no duplicate task.
    apply(config_dir)
    assert State(config_dir).identifier("demo") == identifier

    wait_status(config_dir, "demo", StatusCode.SUCCEEDED)

    destroyed = destroy(config_dir)
    assert destroyed == ["demo"]
    assert State(config_dir).identifier("demo") is None
    assert (config_dir / "work" / "output" / "result.txt").read_text() == "done\n"
    # destroy with nothing applied: no-op
    assert destroy(config_dir) == []


def test_string_escapes_single_pass():
    # "C:\\new" must decode to a literal backslash + 'new', not backslash+\n.
    assert parse_hcl(r'a = "C:\\new"').body["a"] == "C:\\new"
    assert parse_hcl(r'a = "tab\there"').body["a"] == "tab\there"
    assert parse_hcl(r'a = "say \"hi\""').body["a"] == 'say "hi"'


def test_destroy_is_state_driven(config_dir):
    """A resource removed from config (or all .tf files gone) is still
    destroyed from state — Terraform semantics."""
    apply(config_dir)
    identifier = State(config_dir).identifier("demo")
    assert identifier
    (config_dir / "main.tf").unlink()          # user deletes the config
    assert destroy(config_dir) == ["demo"]
    assert State(config_dir).identifier("demo") is None
    from tpu_task import task as task_factory
    from tpu_task.common.cloud import Cloud, Provider

    assert task_factory.list_tasks(Cloud(provider=Provider.LOCAL)) == []


def test_apply_rollback_on_failure(config_dir, monkeypatch):
    """A create that blows up deletes what it made and clears state."""
    from tpu_task.backends.local.task import LocalTask

    real_start = LocalTask.start

    def boom(self):
        raise RuntimeError("injected create failure")

    monkeypatch.setattr(LocalTask, "start", boom)
    with pytest.raises(RuntimeError, match="injected"):
        apply(config_dir)
    assert State(config_dir).identifier("demo") is None
    from tpu_task import task as task_factory
    from tpu_task.common.cloud import Cloud, Provider

    assert task_factory.list_tasks(Cloud(provider=Provider.LOCAL)) == []


def test_reapply_failure_keeps_adopted_task(config_dir, monkeypatch):
    """A transient failure on RE-apply must not delete the live task."""
    from tpu_task.backends.local.task import LocalTask
    from tpu_task import task as task_factory
    from tpu_task.common.cloud import Cloud, Provider

    apply(config_dir)
    identifier = State(config_dir).identifier("demo")
    cloud = Cloud(provider=Provider.LOCAL)
    assert len(task_factory.list_tasks(cloud)) == 1

    def boom(self):
        raise RuntimeError("transient control-plane error")

    monkeypatch.setattr(LocalTask, "start", boom)
    with pytest.raises(RuntimeError, match="transient"):
        apply(config_dir)
    # still in state, still alive
    assert State(config_dir).identifier("demo") == identifier
    assert len(task_factory.list_tasks(cloud)) == 1
    monkeypatch.undo()
    destroy(config_dir)


def test_identifier_persisted_before_create(config_dir, monkeypatch):
    """d.SetId-before-Create parity: a crash after create leaves the
    identifier traceable in state even if read never ran."""
    from tpu_task.backends.local.task import LocalTask

    def boom(self):
        raise RuntimeError("read exploded")

    monkeypatch.setattr(LocalTask, "read", boom)
    results = apply(config_dir)   # read failure is survivable
    assert results["demo"] == {}
    assert State(config_dir).identifier("demo") is not None
    monkeypatch.undo()
    destroy(config_dir)


def test_duplicate_labels_rejected(config_dir):
    (config_dir / "extra.tf").write_text(LOCAL_TF)
    with pytest.raises(HclError, match="duplicate"):
        load_tasks(config_dir)


def test_exclude_string_coerced(tmp_path):
    (tmp_path / "main.tf").write_text('''
      resource "iterative_task" "t" {
        cloud = "local"
        storage { workdir = "." exclude = "cache/**" }
        script = "x"
      }
    ''')
    defn = load_tasks(tmp_path)[0]
    assert build_spec(defn).environment.exclude_list == ["cache/**"]
