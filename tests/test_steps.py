"""Step-runner fail-fast semantics (reference: task/common/steps_test.go:14-54)."""

import pytest

from tpu_task.common.steps import Step, run_steps


def test_runs_all_steps_in_order():
    log = []
    steps = [Step(description=f"step {i}", action=lambda i=i: log.append(i)) for i in range(5)]
    run_steps(steps)
    assert log == [0, 1, 2, 3, 4]


def test_fail_fast():
    log = []

    def boom():
        raise RuntimeError("boom")

    steps = [
        Step(description="one", action=lambda: log.append(1)),
        Step(description="two", action=boom),
        Step(description="three", action=lambda: log.append(3)),
    ]
    with pytest.raises(RuntimeError, match="boom"):
        run_steps(steps)
    assert log == [1]


def test_empty_plan():
    run_steps([])
