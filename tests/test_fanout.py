"""Multi-host fan-out: parallel exec across slice workers (SURVEY.md §7
stage 5 — no reference analog beyond K8s IndexedCompletion)."""

import time

import pytest

from tpu_task import task as task_factory
from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import Environment, Size, StatusCode, Task as TaskSpec
from tpu_task.machine.fanout import ExecResult, LocalTransport, fan_out


def test_fan_out_runs_on_all_workers(tmp_path):
    dirs = []
    for i in range(4):
        d = tmp_path / f"w{i}"
        d.mkdir()
        (d / "tag.txt").write_text(f"worker-{i}\n")
        dirs.append(str(d))
    results = fan_out(dirs, "cat tag.txt", LocalTransport(), timeout=10)
    assert [r.worker_id for r in results] == [0, 1, 2, 3]
    for i, r in enumerate(results):
        assert r.ok and r.stdout == f"worker-{i}\n"


def test_fan_out_isolates_failures(tmp_path):
    dirs = []
    for i in range(3):
        d = tmp_path / f"w{i}"
        d.mkdir()
        dirs.append(str(d))
    results = fan_out(dirs, 'test "$(basename "$PWD")" != w1', LocalTransport())
    assert [r.returncode for r in results] == [0, 1, 0]
    assert not results[1].ok


def test_fan_out_empty():
    assert fan_out([], "true", LocalTransport()) == []


def test_fan_out_timeout(tmp_path):
    d = tmp_path / "w0"
    d.mkdir()
    results = fan_out([str(d)], "sleep 30", LocalTransport(), timeout=0.5)
    assert results[0].returncode == 124
    assert "timeout" in results[0].stderr


@pytest.fixture
def tpu_cloud(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    return Cloud(provider=Provider.TPU, region="us-central2")


def poll(task, predicate, timeout=30.0, period=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        task.read()
        if predicate(task):
            return
        time.sleep(period)
    raise AssertionError(f"condition not reached; status={task.status()}")


def test_exec_on_workers_and_distributed_env(tpu_cloud, tmp_path):
    """exec fans out to every worker of a live slice; every worker got the
    jax.distributed contract (rank / world size / coordinator)."""
    spec = TaskSpec(
        size=Size(machine="v4-32"),  # 4 workers
        environment=Environment(
            # Long sleep keeps workers alive through the exec; the rank lines
            # reach the log stream while the task is still running.
            script='#!/bin/bash\n'
                   'echo "rank=$TPU_TASK_WORKER_ID of=$TPU_TASK_NUM_WORKERS '
                   'coord=$TPU_TASK_COORDINATOR"\n'
                   "sleep 120\n",
        ),
    )
    task = task_factory.new(tpu_cloud, Identifier.deterministic("fanout-exec"), spec)
    task.create()
    try:
        poll(task, lambda t: len(t.get_addresses()) == 4, timeout=60)
        results = task.exec_on_workers("pwd && echo fanned-out")
        assert len(results) == 4
        assert all(r.ok and "fanned-out" in r.stdout for r in results)

        def all_ranks_logged(t):
            logs = "".join(t.logs())
            return all(f"rank={rank} of=4 coord=10.130.0.1:8476" in logs
                       for rank in range(4))

        poll(task, all_ranks_logged)
    finally:
        task.delete()


def test_ssh_transport_materializes_key_once(tmp_path):
    from tpu_task.machine.fanout import SSHTransport

    transport = SSHTransport("-----FAKE KEY-----\n")
    first = transport._ensure_key()
    assert open(first).read() == "-----FAKE KEY-----\n"
    import os
    assert os.stat(first).st_mode & 0o777 == 0o600
    # A 32-worker fan-out reuses the same file: no per-exec rewrite.
    assert all(transport._ensure_key() == first for _ in range(32))
    transport.close()
    assert not os.path.exists(first)
    # close() is idempotent and a later use re-materializes.
    transport.close()
    again = transport._ensure_key()
    assert os.path.exists(again)
    transport.close()
