"""Fleet serving tests: replica front end, session-affine router,
retry-with-re-dispatch under chaos, autoscale, and the serve-gang loop
through the real GangScheduler — all in-process (loopback HTTP replicas),
seconds per test. The real-task chaos soak is tests/test_serve_soak.py.

The exactness spine everything here leans on: a request's stream is a
pure function of (context, sampling key, token index) — never of which
replica ran it, when it was re-dispatched, or who else shared the batch.
That is what lets the router treat ANY replica as a continuation point.
"""

import numpy as np
import pytest

from tpu_task.scheduler import CapacityPool, GangScheduler, TenantQuota
from tpu_task.serve import (
    InProcessServeDriver,
    NoReplicaAvailable,
    QueueDepthAutoscaler,
    ReplicaServer,
    Router,
    ServeFleet,
    ServeSpec,
    replica_script,
    wait_until,
)
from tpu_task.serve.replica import build_engine
from tpu_task.testing.chaos import ChaosSchedule, ChaosTransport

pytestmark = pytest.mark.fleet

RNG = np.random.default_rng(1234)


@pytest.fixture
def replicas():
    """Two started micro replicas, torn down hard at test end."""
    servers = [ReplicaServer(preset="micro").start() for _ in range(2)]
    try:
        yield servers
    finally:
        for server in servers:
            server.stop()


def _router_for(servers, **kwargs):
    router = Router(seed=0, **kwargs)
    router.set_replicas({
        f"r{i}": {"url": server.url, "boot_id": server.boot_id}
        for i, server in enumerate(servers)})
    return router


def _assert_trace_continuity(router, replicas, fid, n_tokens):
    """PR 11 pin: a re-dispatched stream is ONE trace end to end —

    * every dispatch span is a child of the request's root span, in the
      same trace;
    * the dispatch spans' [token_start, token_end) ranges tile
      [0, n_tokens) exactly once (no token delivered twice or dropped
      across the failover);
    * every replica-side engine span of the trace parent-links to one of
      the router's dispatch spans (the cross-process header hop).
    """
    request = router.request(fid)
    trace_id = request.trace.trace_id
    dispatches = [span for span in router.obs.tracer.finished()
                  if span.name == "dispatch"
                  and span.attrs.get("fid") == fid]
    assert len(dispatches) >= 2, "no re-dispatch recorded"
    assert {span.trace_id for span in dispatches} == {trace_id}
    assert {span.parent_id for span in dispatches} == \
        {request.trace.span_id}
    delivered = [span for span in dispatches if "token_end" in span.attrs]
    covered = []
    for span in sorted(delivered,
                       key=lambda span: span.attrs["token_start"]):
        covered.extend(range(span.attrs["token_start"],
                             span.attrs["token_end"]))
    assert covered == list(range(n_tokens))
    dispatch_ids = {span.span_id for span in dispatches}
    engine_spans = [span for server in replicas
                    for span in server.obs.tracer.finished()
                    if span.trace_id == trace_id]
    assert engine_spans, "no replica-side spans joined the trace"
    assert all(span.parent_id in dispatch_ids for span in engine_spans)
    return dispatches, engine_spans


def _reference_streams(router, fids, preset="micro"):
    """What a single uninterrupted engine produces for the same requests
    (same prompts, same router-derived keys)."""
    import jax.numpy as jnp

    engine = build_engine(preset)
    rids = {}
    for fid in fids:
        request = router.request(fid)
        rids[fid] = engine.submit(
            request.prompt, request.max_new_tokens,
            temperature=request.temperature, top_p=request.top_p,
            eos_token=request.eos_token,
            key=jnp.asarray(np.asarray(request.key, np.uint32)))
    out = engine.drain()
    return {fid: out[rid] for fid, rid in rids.items()}


# -- replica HTTP front end ---------------------------------------------------


def test_replica_front_end_submit_stream_stats(replicas):
    replica = replicas[0]
    router = _router_for([replica])
    fid = router.submit(RNG.integers(0, 64, size=6), 8,
                        temperature=0.6, top_p=0.9)
    out = router.drain(deadline_s=60)
    assert len(out[fid]) == 8
    stats = replica.stats()
    assert stats["slots"] >= 1 and stats["draining"] is False
    assert stats["boot_id"] == replica.boot_id
    # Offset-based stream: re-fetching an old offset returns the same
    # suffix (at-least-once transport → exactly-once token delivery).
    rid = router.request(fid).rid
    again = replica.stream(rid, 0, wait_ms=0)
    assert again["tokens"] == out[fid]
    assert replica.stream(rid, 5, wait_ms=0)["tokens"] == out[fid][5:]


def test_replica_rejects_malformed_key_at_the_400_boundary(replicas):
    """A wrong-shape sampling key must be rejected at submission (400),
    never stored to detonate later inside the step-loop thread; and a
    step-loop failure drains the replica instead of wedging it silently."""
    replica = replicas[0]
    with pytest.raises(ValueError, match="2 uint32 words"):
        replica.submit({"prompt": [1], "max_new_tokens": 2,
                        "key": [1, 2, 3]})
    with pytest.raises(ValueError):
        replica.submit({"prompt": [1], "max_new_tokens": 2,
                        "key": "not-a-key"})
    assert not replica.draining                  # rejected at the boundary

    # Step-loop failure → drain, not a silent wedge: healthz/stream
    # advertise draining so the router fails over.
    broken = ReplicaServer(preset="micro").start()
    try:
        rid = broken.submit({"prompt": [1, 2], "max_new_tokens": 4})
        broken.engine.step = None                # next loop iteration dies
        assert wait_until(lambda: broken.draining, 10)
        assert broken.stream(rid, 0, wait_ms=0)["draining"] is True
        # The step-loop failure is a STRUCTURED error event (exception
        # type + message on the registry/tracer), not only a stderr
        # traceback nobody syncs.
        errors = [span for span in broken.obs.tracer.finished()
                  if span.status == "error"]
        assert errors and errors[0].attrs["path"] == "step_loop"
        assert errors[0].attrs["exc_type"] == "TypeError"
        assert broken.stats()["obs"]["replica.errors"]["value"] >= 1
    finally:
        broken.stop()


def test_request_handler_failure_records_error_span_and_500(replicas):
    """The PR 11 bugfix satellite: a request-handler failure answers 500
    WITH the message (unchanged contract) and additionally lands a
    structured error span — exception type/message, linked to the
    request's trace via the propagated header — on the replica's ring,
    so `obs trace` and the durable export see the failed request."""
    import urllib.error
    import urllib.request

    from tpu_task.obs import TRACE_HEADER, Tracer

    replica = replicas[0]

    def boom(payload, trace=None):
        raise RuntimeError("pool corrupted")

    replica.submit = boom
    tracer = Tracer("client")
    root = tracer.start("request", fid=0)
    request = urllib.request.Request(
        replica.url + "/submit",
        data=b'{"prompt": [1], "max_new_tokens": 2}',
        headers={"Content-Type": "application/json",
                 TRACE_HEADER: root.ctx.to_header()})
    with pytest.raises(urllib.error.HTTPError) as info:
        urllib.request.urlopen(request, timeout=10)
    assert info.value.code == 500
    assert "pool corrupted" in info.value.read().decode()

    errors = [span for span in replica.obs.tracer.finished()
              if span.status == "error"]
    assert len(errors) == 1
    span = errors[0]
    assert span.attrs["exc_type"] == "RuntimeError"
    assert span.attrs["error"] == "pool corrupted"
    assert span.attrs["path"] == "/submit"
    assert span.trace_id == root.trace_id        # joined the caller's trace
    assert span.parent_id == root.span_id
    assert replica.stats()["obs"]["replica.errors"]["value"] == 1


def test_replica_draining_rejects_submit_with_409(replicas):
    """A draining replica answers /submit with 409 (outside the transport
    retry set); the router quarantines it for dispatch and the request
    queues instead of burning the backoff ladder against it."""
    replica = replicas[0]
    replica.begin_drain()
    router = _router_for([replica])
    fid = router.submit([1, 2, 3], 4)
    router.pump()
    assert router.request(fid).status == "queued"
    assert router.replicas()["r0"]["healthy"] is False
    assert router.transport_faults == 0       # draining is policy, not fault
    with pytest.raises(NoReplicaAvailable):
        router.pick([1, 2, 3])


# -- router dispatch policy ---------------------------------------------------


def test_affinity_same_prefix_lands_on_same_replica_until_drain(replicas):
    """Same-prefix requests pin to one replica (the prefix cache's hit
    condition); once that replica drains, new dispatch moves off it."""
    router = _router_for(replicas, affinity_tokens=16)
    head = RNG.integers(0, 64, size=16)

    def prompt():
        return np.concatenate([head, RNG.integers(0, 64, size=2)])

    fids = [router.submit(prompt(), 4) for _ in range(4)]
    router.pump()
    homes = {router.request(fid).replica for fid in fids}
    assert len(homes) == 1
    home = homes.pop()
    router.drain(deadline_s=60)

    victim = replicas[int(home[1:])]
    victim.begin_drain()
    late = [router.submit(prompt(), 4) for _ in range(2)]
    router.pump()
    new_homes = {router.request(fid).replica for fid in late}
    assert new_homes and home not in new_homes
    router.drain(deadline_s=60)


@pytest.mark.slow
def test_dispatch_spills_to_least_loaded_past_threshold(replicas):
    router = _router_for(replicas, affinity_tokens=16, spill_load=2)
    head = RNG.integers(0, 64, size=16)
    # Enough same-prefix long requests to pass the spill threshold: the
    # overflow must land on the other replica instead of queueing forever
    # behind the affinity choice.
    fids = [router.submit(np.concatenate([head, [i]]), 24)
            for i in range(6)]
    router.pump()
    homes = [router.request(fid).replica for fid in fids]
    assert len(set(homes)) == 2
    # ... but the FIRST requests (below threshold) stayed on affinity.
    assert len({homes[0], homes[1]}) == 1
    router.drain(deadline_s=120)


# -- failover: exactness across re-dispatch -----------------------------------


@pytest.mark.perf
def test_hard_kill_mid_stream_sampled_streams_identical(replicas):
    """Kill a replica's socket mid-generation: every stream completes on
    the sibling and every SAMPLED stream is token-identical to an
    uninterrupted single-engine run — the serve-subsystem extension of
    the PR 8 preemption-replay pin."""
    router = _router_for(replicas, retries=0, timeout=5.0)
    fids = [router.submit(RNG.integers(0, 64, size=8), 40,
                          temperature=0.8, top_p=0.9) for _ in range(4)]
    # Wait until every request has first tokens, then kill the replica of
    # a request that is provably mid-stream.
    assert wait_until(
        lambda: all(router.request(fid).tokens for fid in fids),
        30, tick=router.pump, period=0)
    open_fids = [fid for fid in fids
                 if len(router.request(fid).tokens) < 40]
    assert open_fids, "every stream already finished — nothing mid-stream"
    victim = router.request(open_fids[0]).replica
    replicas[int(victim[1:])].stop()          # hard: connection refused
    out = router.drain(deadline_s=120)
    assert all(len(out[fid]) == 40 for fid in fids)
    assert router.request(open_fids[0]).dispatches >= 2
    assert router.redispatches > 0
    assert out == _reference_streams(router, fids)
    # The failover is one trace: dispatch spans tile every delivered
    # token index exactly once, and both replicas' engine spans (the
    # hard-killed one's finished phases included) link under them.
    _assert_trace_continuity(router, replicas, open_fids[0], 40)


@pytest.mark.slow
def test_graceful_drain_serves_suffix_then_fails_over(replicas):
    """begin_drain (the SIGTERM path): the draining replica still answers
    /stream with what it emitted, the router takes that suffix and
    re-dispatches the remainder — no token recomputed twice, stream
    identical to an uninterrupted run."""
    router = _router_for(replicas)
    fid = router.submit(RNG.integers(0, 64, size=8), 24,
                        temperature=0.7, top_p=0.95)
    assert wait_until(lambda: len(router.request(fid).tokens) >= 2,
                      30, tick=router.pump, period=0)
    victim = replicas[int(router.request(fid).replica[1:])]
    exported = victim.begin_drain()
    assert any(record["tokens"] for record in exported)
    record = next(r for r in exported if r["tokens"])
    assert record["key"] is not None and record["prompt"]
    out = router.drain(deadline_s=120)
    assert len(out[fid]) == 24
    assert router.request(fid).dispatches == 2
    assert out == _reference_streams(router, [fid])
    # Graceful-drain trace continuity: additionally, the victim's decode
    # span ended as "exported" at the drain boundary and the sibling's
    # decode span picks up at exactly that token index — the engine-side
    # halves of the stream tile [0, 24) with no overlap.
    _, engine_spans = _assert_trace_continuity(router, replicas, fid, 24)
    decodes = sorted(
        (span for span in engine_spans if span.name == "engine.decode"),
        key=lambda span: span.attrs["token_start"])
    assert [span.status for span in decodes] == ["exported", "ok"]
    assert decodes[0].attrs["token_start"] == 0
    assert decodes[0].attrs["token_end"] == \
        decodes[1].attrs["token_start"]
    assert decodes[1].attrs["token_end"] == 24
    assert len({span.source for span in decodes}) == 2  # two replicas


@pytest.mark.slow
def test_chaos_transport_resets_and_timeouts_no_dup_no_drop(replicas):
    """Seeded connection resets + timeouts on EVERY router HTTP call:
    requests all complete with streams identical to the fault-free
    reference — offset-based pulls make the at-least-once transport
    deliver each token exactly once, and quarantined replicas rejoin via
    membership refresh instead of staying lost."""
    schedule = ChaosSchedule(seed=20260804)
    chaos = ChaosTransport(schedule, reset_rate=0.08, timeout_rate=0.05)
    router = _router_for(replicas, urlopen=chaos, retries=1, timeout=5.0,
                         quarantine_s=0.01)
    endpoints = {f"r{i}": {"url": s.url, "boot_id": s.boot_id}
                 for i, s in enumerate(replicas)}
    fids = [router.submit(RNG.integers(0, 64, size=6), 10,
                          temperature=0.5, top_p=0.9) for _ in range(6)]

    # Chaos quarantines replicas; the fleet's membership refresh (the
    # same set_replicas call ServeFleet.tick makes) heals a lapsed
    # quarantine — same boot id, same record, health restored.
    deadline_rounds = 3000
    while router.pump(wait_ms=5) and deadline_rounds:
        router.set_replicas(endpoints)
        deadline_rounds -= 1
    assert deadline_rounds, "requests did not complete under chaos"
    out = {fid: router.result(fid) for fid in fids}
    assert all(len(stream) == 10 for stream in out.values())
    assert schedule.injected, "chaos never fired — rates too low"
    assert out == _reference_streams(router, fids)


@pytest.mark.slow
def test_all_replicas_down_requests_queue_then_recover(replicas):
    router = _router_for(replicas, retries=0, timeout=2.0)
    for replica in replicas:
        replica.begin_drain()
    fid = router.submit(RNG.integers(0, 64, size=4), 4)
    router.pump()
    assert router.request(fid).status != "done"
    # A fresh replica joins (new boot id): the queued request dispatches.
    from tpu_task.serve import probe_healthy

    fresh = ReplicaServer(preset="micro").start()
    try:
        assert wait_until(lambda: probe_healthy(fresh.url), 30)
        router.set_replicas({"r9": {"url": fresh.url,
                                    "boot_id": fresh.boot_id}})
        out = router.drain(deadline_s=120)
        assert len(out[fid]) == 4
    finally:
        fresh.stop()


def test_malformed_request_fails_terminally_without_poisoning_fleet(replicas):
    """A replica's 4xx indicts the REQUEST, not the replica: the bad
    submission fails terminally with the rejection surfaced, every
    replica stays healthy, and later valid requests flow normally."""
    router = _router_for(replicas)
    bad = router.submit([1, 2, 3], 4, top_p=0.9)   # top_p needs temp > 0
    router.pump()
    assert router.request(bad).status == "failed"
    with pytest.raises(RuntimeError, match="rejected"):
        router.result(bad)
    assert all(info["healthy"] for info in router.replicas().values())
    good = router.submit([1, 2, 3], 4)
    out = router.drain(deadline_s=60)
    assert len(out[good]) == 4

    # Same rejection reached from pump()'s dispatch path (request queued
    # first): the failure must be terminal there too — a FAILED request
    # must never resurrect to QUEUED and re-POST forever.
    router2 = Router(seed=1)
    bad2 = router2.submit([7], 4, top_p=0.5)       # queues: no replicas yet
    router2.set_replicas({name: {"url": s.url, "boot_id": s.boot_id}
                          for name, s in zip(("r0", "r1"), replicas)})
    router2.drain(deadline_s=30)                   # must terminate
    assert router2.request(bad2).status == "failed"
    for _ in range(3):
        router2.pump()
    assert router2.request(bad2).status == "failed"


# -- autoscale ----------------------------------------------------------------


def test_autoscaler_hysteresis_and_bounds():
    scaler = QueueDepthAutoscaler(min_replicas=1, max_replicas=3,
                                  high=2.0, low=0.25, patience=2)
    # Two over-threshold samples → +1; counter resets after the decision.
    assert scaler.observe(8, 2) == 2
    assert scaler.observe(8, 2) == 3
    assert scaler.observe(8, 3) == 3
    assert scaler.observe(8, 3) == 3          # capped at max_replicas
    # Idle samples → -1 after patience, never below the floor.
    assert scaler.observe(0, 3) == 3
    assert scaler.observe(0, 3) == 2
    assert scaler.observe(0, 2) == 2
    assert scaler.observe(0, 2) == 1
    assert scaler.observe(0, 1) == 1
    assert scaler.observe(0, 1) == 1          # floored at min_replicas
    # A mid-pressure sample resets both streaks.
    scaler2 = QueueDepthAutoscaler(patience=2, high=2.0, low=0.25)
    scaler2.observe(8, 2)
    scaler2.observe(1, 2)                     # between low and high
    assert scaler2.observe(8, 2) == 2         # streak restarted
    # Exactly at capacity is NOT idle: zero backlog but a busy fleet must
    # never scale down (it would shed replicas mid-stream and flap).
    scaler3 = QueueDepthAutoscaler(patience=1, high=2.0, low=0.25)
    for _ in range(3):
        assert scaler3.observe(0, 2, busy=8) == 2
    assert scaler3.observe(0, 2, busy=0) == 1  # genuinely idle → down
    with pytest.raises(ValueError):
        QueueDepthAutoscaler(min_replicas=0)
    with pytest.raises(ValueError):
        QueueDepthAutoscaler(low=3.0, high=2.0)


# -- the serve-gang loop through the real scheduler ---------------------------


def _fleet(monkeypatch, replicas=2, autoscaler=None, quota_chips=32):
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.05")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_CAP", "0.2")
    driver = InProcessServeDriver()
    scheduler = GangScheduler(
        CapacityPool([quota_chips]),
        {"svc": TenantQuota(chips=quota_chips, weight=1.0)}, driver)
    router = Router(seed=3)
    spec = ServeSpec(service="chat", tenant="svc", replicas=replicas,
                     preset="micro")
    fleet = ServeFleet(scheduler, spec, router, autoscaler=autoscaler)
    return fleet, driver, scheduler, router


@pytest.fixture
def torn_down():
    fleets = []
    yield fleets
    for fleet in fleets:
        for task_id in list(fleet.scheduler.driver.running_ids()):
            fleet.scheduler.driver._stop(task_id, graceful=False)


@pytest.mark.slow
def test_serve_gangs_requeue_through_scheduler_governor(
        monkeypatch, torn_down):
    """The in-process twin of the chaos soak: replica gangs placed by the
    scheduler, a chaos hard-kill mid-stream, router failover to the
    sibling, and the killed gang requeued through the scheduler's backoff
    governor — back in membership with a NEW boot id."""
    fleet, driver, scheduler, router = _fleet(monkeypatch)
    torn_down.append(fleet)
    fleet.launch()
    fleet.tick()
    assert len(router.replicas()) == 2
    for task_id in fleet._gangs:
        assert scheduler.queue.tasks[task_id].payload["kind"] == "serve"

    fids = [router.submit(RNG.integers(0, 64, size=8), 16) for _ in range(4)]
    assert wait_until(
        lambda: all(router.request(fid).tokens for fid in fids),
        30, tick=router.pump, period=0)
    victim = next(router.request(fid).replica for fid in fids)
    old_boot = router.replicas()[victim]["boot_id"]
    driver.kill(victim, graceful=False)

    out = router.drain(deadline_s=120, on_idle=fleet.tick)
    assert all(len(out[fid]) == 16 for fid in fids)
    assert out == _reference_streams(router, fids)

    # The scheduler may not have observed the kill yet (the sibling can
    # absorb every stream between ticks) — tick until the governor does,
    # then until the backoff gate re-places the gang.
    task = scheduler.queue.tasks[victim]
    assert wait_until(lambda: task.preemptions >= 1, 30,
                      tick=fleet.tick, period=0.02)
    assert task.attempts >= 1                 # chaos charges the budget
    assert wait_until(lambda: task.state == "placed", 30,
                      tick=fleet.tick, period=0.02)
    fleet.tick()
    assert router.replicas()[victim]["boot_id"] != old_boot
    # The recovered replica serves again.
    late = router.submit(RNG.integers(0, 64, size=4), 4)
    assert len(router.drain(deadline_s=60, on_idle=fleet.tick)[late]) == 4


@pytest.mark.slow
def test_fleet_autoscales_up_under_backlog_and_down_when_idle(
        monkeypatch, torn_down):
    scaler = QueueDepthAutoscaler(min_replicas=1, max_replicas=3,
                                  high=1.0, low=0.25, patience=1)
    fleet, driver, scheduler, router = _fleet(
        monkeypatch, replicas=1, autoscaler=scaler)
    torn_down.append(fleet)
    fleet.launch()
    fleet.tick()
    assert fleet.live_replicas() == 1

    # Backlog far past one replica's slots → scale up through the
    # scheduler (new serve gang admitted, endpoint joins the router).
    fids = [router.submit(RNG.integers(0, 64, size=6), 12)
            for _ in range(12)]
    fleet.tick()
    assert fleet.live_replicas() >= 2
    assert wait_until(lambda: len(router.replicas()) >= 2, 30,
                      tick=fleet.tick, period=0.02)
    out = router.drain(deadline_s=180, on_idle=fleet.tick)
    assert all(len(out[fid]) == 12 for fid in fids)

    # Idle ticks → scale back down to the floor; retired gangs leave the
    # scheduler terminally instead of lingering as running batch tasks.
    assert wait_until(lambda: fleet.live_replicas() == 1, 30,
                      tick=fleet.tick, period=0.02)
    retired = [task for task in scheduler.queue.tasks.values()
               if task.failure == "retired"]
    assert retired and all(task.state == "succeeded" for task in retired)
    assert scaler.decisions and scaler.decisions[0].startswith("up:")


def test_cli_sched_status_renders_serve_kind(tmp_path, capsys, monkeypatch,
                                             torn_down):
    """`sched status` shows serve gangs as service replicas (KIND column),
    not perpetually-running batch tasks — the PR's CLI satellite."""
    from tpu_task.cli.main import main as cli_main

    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.05")
    remote = str(tmp_path / "sched")
    driver = InProcessServeDriver()
    scheduler = GangScheduler(
        CapacityPool([32]),
        {"svc": TenantQuota(chips=32, weight=1.0),
         "lab": TenantQuota(chips=16, weight=1.0)}, driver, remote=remote)
    router = Router(seed=0)
    fleet = ServeFleet(scheduler, ServeSpec(
        service="chat", tenant="svc", replicas=2, preset="micro"),
        router)
    torn_down.append(fleet)
    scheduler.submit("lab", "v4-8", work=100.0, task_id="batch-0")
    fleet.launch()
    fleet.tick()

    assert cli_main(["sched", "status", "--remote", remote]) == 0
    out = capsys.readouterr().out
    lines = out.strip().splitlines()
    header = lines[0].split()
    assert header[:5] == ["TENANT", "KIND", "QUEUED", "RUNNING", "CHIPS"]
    serve_rows = [line for line in lines if " serve " in f" {line} "]
    assert len(serve_rows) == 1
    assert "2 replicas" in serve_rows[0]
    assert "serve: chat (svc) — 2 replicas placed" in out
    batch_rows = [line.split() for line in lines[1:]
                  if len(line.split()) > 1 and line.split()[1] == "batch"]
    assert {row[0] for row in batch_rows} == {"lab"}


def test_serve_spec_script_and_payload():
    spec = ServeSpec(service="chat", tenant="svc", replicas=2,
                     preset="tiny", serving={"slots": 2},
                     prefill_serving={"chunk_tokens": 64},
                     kv_bucket="/tmp/kv")
    script = replica_script(spec, python="python3.11")
    assert script.startswith("#!/bin/bash\n")
    assert "-m tpu_task.serve.replica" in script
    assert "--preset tiny" in script and '"slots": 2' in script
    assert "--kv-bucket '/tmp/kv'" in script
    payload = spec.payload(3)
    assert payload == {"kind": "serve", "service": "chat", "replica": "3",
                       "preset": "tiny", "role": "decode",
                       "tp": "1", "ep": "1",
                       "serving": '{"slots": 2}'}
    # The prefill role's serving overrides land in its payload + script.
    assert spec.payload(0, role="prefill")["serving"] == \
        '{"chunk_tokens": 64, "slots": 2}'
    assert '"chunk_tokens": 64' in replica_script(spec, role="prefill")


# -- sharded replicas: tp×ep gangs (ROADMAP item 1) ---------------------------


class _NullDriver:
    """Accounting-only GangDriver: placements succeed, nothing launches
    — the scheduler math is the test subject, not the replicas."""

    self_recovering = False

    def launch(self, task):
        pass

    def poll(self, task):
        from tpu_task.scheduler import driver as driver_module

        return driver_module.RUNNING

    def preempt(self, task, graceful=True):
        pass

    def release(self, task):
        pass

    def failure_reason(self, task):
        return "task-failed"


@pytest.mark.moe
def test_serve_spec_tp_ep_gang_accounting():
    """The scheduler-accounting satellite: a sharded replica's gang
    reserves EXACTLY tp×ep chips — derived accelerator, quota math, and
    the status snapshot's serve chips column all agree — and the
    dishonest combinations fail loudly at construction."""
    spec = ServeSpec(service="moe", tenant="svc", replicas=2,
                     preset="moe", tp=2, ep=2)
    assert spec.chips == 4
    assert spec.gang_accelerator == "v4-8"        # 4 chips exactly
    assert spec.payload(0)["tp"] == "2" and spec.payload(0)["ep"] == "2"
    assert "--tp 2 --ep 2" in replica_script(spec)
    # Explicit accelerator must match tp×ep; fleet KV is single-chip.
    with pytest.raises(ValueError, match="chips"):
        ServeSpec(service="x", tenant="t", accelerator="v4-8", tp=8, ep=1)
    with pytest.raises(ValueError, match="single-chip"):
        ServeSpec(service="x", tenant="t", tp=2, kv_bucket="/tmp/kv")
    with pytest.raises(ValueError, match="tp and ep"):
        ServeSpec(service="x", tenant="t", tp=0)

    scheduler = GangScheduler(
        CapacityPool([16]), {"svc": TenantQuota(chips=16, weight=1.0)},
        _NullDriver())
    router = Router(seed=0)
    fleet = ServeFleet(scheduler, spec, router)
    fleet.launch()
    scheduler.tick()
    for task_id in fleet._gangs:
        assert scheduler.queue.tasks[task_id].gang.total_chips == 4
    status = scheduler.status()["tenants"]["svc"]
    assert status["serve"]["chips"] == 8          # 2 replicas × tp×ep
    assert status["running_chips"] == 8
    # A third 4-chip gang still fits the 16-chip pool; a tp8×ep4 one
    # could never (quota says so before anything launches).
    with pytest.raises(ValueError, match="chips"):
        scheduler.submit("svc", ServeSpec(
            service="big", tenant="svc", tp=8, ep=4).gang_accelerator)


@pytest.mark.slow
@pytest.mark.moe
def test_sharded_replica_preemption_handoff_token_identical(monkeypatch,
                                                           torn_down):
    """The preemption half of the tentpole's exit: a mid-stream graceful
    preemption of a SHARDED (tp2×ep2 MoE) replica drains, exports, and
    fails over through the existing inflight seam — every affected
    stream continues on the sibling token-identically to an
    uninterrupted single-chip dense reference."""
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.05")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_CAP", "0.2")
    driver = InProcessServeDriver()
    scheduler = GangScheduler(
        CapacityPool([16]), {"svc": TenantQuota(chips=16, weight=1.0)},
        driver)
    router = Router(seed=3)
    spec = ServeSpec(service="moe", tenant="svc", replicas=2,
                     preset="moe", tp=2, ep=2)
    fleet = ServeFleet(scheduler, spec, router)
    torn_down.append(fleet)
    fleet.launch()
    assert wait_until(lambda: len(fleet.refresh_endpoints()) == 2, 60,
                      tick=fleet.tick, period=0.05)
    fleet.tick()

    fids = [router.submit(RNG.integers(0, 64, size=8), 24)
            for _ in range(4)]
    assert wait_until(
        lambda: all(router.request(fid).tokens for fid in fids),
        30, tick=router.pump, period=0)
    live = [fid for fid in fids
            if router.request(fid).status not in ("done", "failed")
            and router.request(fid).replica]
    assert live, "every stream finished before the kill could land"
    victim = router.request(live[0]).replica
    affected = [fid for fid in live
                if router.request(fid).replica == victim]
    driver.kill(victim, graceful=True)

    out = router.drain(deadline_s=120, on_idle=fleet.tick)
    assert all(len(out[fid]) == 24 for fid in fids)
    assert out == _reference_streams(router, fids, preset="moe")
    # Every stream open on the victim at kill time failed over.
    assert router.redispatches >= len(affected) >= 1


@pytest.mark.slow
@pytest.mark.moe
def test_fleet_serves_moe_exceeding_one_chip_at_ep4(torn_down):
    """THE acceptance criterion: an MoE config whose expert weights
    exceed one chip's (notional) weight budget serves end-to-end through
    ServeFleet at ep=4 — each gang honestly reserves 4 chips, each
    device holds 1/4 of the expert table, and greedy streams are
    bit-identical to the single-chip dense-dispatch reference."""
    driver = InProcessServeDriver()
    scheduler = GangScheduler(
        CapacityPool([8]), {"svc": TenantQuota(chips=8, weight=1.0)},
        driver)
    router = Router(seed=5)
    spec = ServeSpec(service="bigmoe", tenant="svc", replicas=1,
                     preset="moe", tp=1, ep=4)
    fleet = ServeFleet(scheduler, spec, router)
    torn_down.append(fleet)
    fleet.launch()
    assert wait_until(lambda: len(fleet.refresh_endpoints()) == 1, 60,
                      tick=fleet.tick, period=0.05)
    fleet.tick()
    task = scheduler.queue.tasks[fleet._gangs[0]]
    assert task.gang.total_chips == 4

    server = next(iter(driver._servers.values()))
    eng = server.engine
    assert eng.stats()["ep"] == 4
    expert_bytes = sum(
        leaf.nbytes for layer in eng.params["layers"]
        if "w_in" in layer for leaf in (layer["w_in"], layer["w_out"]))
    budget = 32 * 1024                 # notional per-chip expert budget
    assert expert_bytes > budget                  # too big for one chip
    for layer in eng.params["layers"]:
        if "w_in" in layer:
            shard = layer["w_in"].addressable_shards[0].data.nbytes
            assert shard * 4 == layer["w_in"].nbytes
            assert 2 * shard <= budget            # w_in + w_out fit

    fids = [router.submit(RNG.integers(0, 64, size=6), 8)
            for _ in range(3)]
    out = router.drain(deadline_s=120, on_idle=fleet.tick)
    assert out == _reference_streams(router, fids, preset="moe")
