"""Serve-as-a-task chaos soak (`make serve-soak`): replica gangs as REAL
fake-mode TPU tasks, a seeded mid-stream replica preemption through the
chaos plane, and the full recovery loop — drain/export on SIGTERM, router
re-dispatch to the sibling, requeue through the PR 3 governor (durable
events), re-announce, rejoin.

This is the ROADMAP item 5 exit criterion end to end: the engine fleet is
scheduled like any training gang (PR 7), each replica machine is the
paper's one-script unit where the script happens to be
``python -m tpu_task.serve.replica`` (PR 5/8/9 engine behind HTTP on the
PR 2 pooled transport), preemption recovery is the unchanged PR 3
machinery, and the client-visible contract is: every request completes
and every greedy stream is BIT-IDENTICAL to an unpreempted single-engine
run. Replayable via TPU_TASK_CHAOS_SEED.
"""

import json
import os
import sys

import numpy as np
import pytest

from tpu_task.scheduler import CapacityPool, GangScheduler, TenantQuota
from tpu_task.scheduler.driver import TpuTaskDriver
from tpu_task.serve import (
    Router,
    ServeFleet,
    ServeSpec,
    bucket_endpoint_source,
    replica_script,
    wait_until,
)
from tpu_task.serve.replica import build_engine
from tpu_task.testing.chaos import ChaosSchedule, ChaosTpuClient

pytestmark = [pytest.mark.fleet, pytest.mark.chaos, pytest.mark.slow]

SEED = int(os.environ.get("TPU_TASK_CHAOS_SEED", "20260804"))
MAX_NEW = 40     # long streams: the preemption must land mid-generation


def test_serve_fleet_survives_midstream_replica_preemption(tmp_path,
                                                           monkeypatch):
    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_HEARTBEAT_PERIOD", "0.5")
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "0")  # liveness off
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.2")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_CAP", "1.0")
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "10")

    from tpu_task import task as task_factory
    from tpu_task.common.cloud import Cloud, Provider
    from tpu_task.common.identifier import Identifier
    from tpu_task.common.values import (
        SPOT_ENABLED, Environment, Size, Task as TaskSpec,
    )

    spec = ServeSpec(service="chat", tenant="serve", replicas=2,
                     accelerator="v4-8", preset="micro")
    script = replica_script(spec, python=sys.executable)
    cloud = Cloud(provider=Provider.TPU, region="us-central2")
    backends = {}

    def factory(task):
        backend = task_factory.new(
            cloud, Identifier.deterministic(task.task_id),
            TaskSpec(size=Size(machine=task.gang.accelerator),
                     environment=Environment(script=script),
                     spot=SPOT_ENABLED))
        backends[task.task_id] = backend
        return backend

    driver = TpuTaskDriver(factory, delete_on_release=False)
    scheduler = GangScheduler(
        CapacityPool([8]), {"serve": TenantQuota(chips=8, weight=1.0)},
        driver)
    router = Router(seed=SEED, retries=0, timeout=5.0)
    fleet = ServeFleet(
        scheduler, spec, router,
        endpoint_source=bucket_endpoint_source(
            lambda task_id: backends[task_id]._bucket_dir
            if task_id in backends else str(tmp_path / "nowhere")))

    schedule = ChaosSchedule(seed=SEED)
    rng = np.random.default_rng(SEED)

    try:
        fleet.launch()
        # Replica machines bootstrap (subprocess jax import + engine
        # build) and announce endpoints through their task buckets.
        assert wait_until(lambda: len(fleet.refresh_endpoints()) == 2,
                          240, tick=fleet.tick, period=0.2), \
            "replica endpoints never announced"
        fleet.tick()
        assert len(router.replicas()) == 2

        # Mixed greedy workload, shared prefixes included (the affinity +
        # prefix-cache shape), long streams so preemption lands mid-way.
        head = rng.integers(0, 64, size=6)
        prompts = [np.concatenate([head, rng.integers(0, 64, size=2)])
                   if i % 2 == 0 else rng.integers(0, 64, size=8)
                   for i in range(10)]
        fids = [router.submit(p, MAX_NEW) for p in prompts]

        # First tokens everywhere = compiles done, streams in flight.
        assert wait_until(
            lambda: all(router.request(fid).tokens for fid in fids),
            240, tick=lambda: (router.pump(), fleet.tick()), period=0)
        open_fids = [fid for fid in fids
                     if len(router.request(fid).tokens) < MAX_NEW]
        assert open_fids, "streams finished before the chaos window"

        # Seeded victim: preempt a replica with open streams, THROUGH the
        # chaos plane (graceful = the cloud's SIGTERM reclaim notice).
        candidates = sorted({router.request(fid).replica
                             for fid in open_fids})
        victim = schedule.derive("serve-soak").choice(candidates)
        victim_backend = backends[victim]
        chaos = ChaosTpuClient(victim_backend.client, schedule)
        victim_backend.client = chaos
        node = victim_backend._qr_name(0)
        old_boot = router.replicas()[victim]["boot_id"]
        chaos.preempt_at(0.0, node, graceful=True)

        # Drain the workload while the preemption fires: the router takes
        # the draining replica's suffix, re-dispatches to the sibling, and
        # the reconciler requeues the gang underneath.
        out = router.drain(deadline_s=240, on_idle=fleet.tick)
        assert all(len(out[fid]) == MAX_NEW for fid in fids)
        assert any(kind == "preempt" for kind in
                   (fault.kind for fault in schedule.injected)), \
            "chaos preemption never fired"
        redispatched = [fid for fid in fids
                        if router.request(fid).dispatches > 1]
        assert redispatched, "no stream survived a mid-flight preemption"

        # Bit-identical to an unpreempted run: one local engine, same
        # preset, same prompts (greedy = pure function of context).
        engine = build_engine(spec.preset)
        ref = {}
        for fid in fids:
            ref[fid] = engine.submit(router.request(fid).prompt, MAX_NEW)
        ref_out = engine.drain()
        for fid in fids:
            assert out[fid] == ref_out[ref[fid]], fid

        # The drained replica exported its in-flight state durably (the
        # agent's final sync shipped it): prompt + tokens + sampling key.
        drain_path = os.path.join(
            victim_backend._bucket_dir, "data", "inflight.json")
        assert wait_until(lambda: os.path.exists(drain_path), 30,
                          tick=fleet.tick)
        exported = json.load(open(drain_path))
        assert exported["boot_id"] == old_boot
        assert any(record["tokens"] and record["key"]
                   for record in exported["inflight"]), \
            "drain export carries no mid-stream request"

        # Recovery rode the PR 3 governor: durable requeue/recover events
        # in the task mailbox, and the replica re-announced with a new
        # boot id and serves again.
        assert wait_until(
            lambda: router.replicas().get(victim, {}).get("boot_id",
                                                          old_boot)
            != old_boot, 240, tick=fleet.tick, period=0.2), \
            "preempted replica never rejoined"
        codes = [event.code for event in victim_backend.events()]
        assert "recover" in codes, codes

        late = router.submit(rng.integers(0, 64, size=8), 8)
        late_out = router.drain(deadline_s=120, on_idle=fleet.tick)
        assert len(late_out[late]) == 8
        # Replayability record: the injected-fault flight log is seeded.
        assert schedule.injected[0].kind == "preempt"

        # PR 11 acceptance: the seeded mid-stream preemption renders ONE
        # waterfall — submit/dispatch (router, in-process), queue/prefill/
        # decode (replica subprocesses, spans shipped to the task buckets
        # by the SAME data sync that carried inflight.json), the victim's
        # drain/export leg (status=exported), and the sibling's
        # re-dispatch as a child span of the same trace — and the whole
        # thing exports as valid Chrome-trace JSON.
        from tpu_task.obs import chrome_trace, read_spans, render_waterfall
        from tpu_task.storage.backends import open_backend

        fid = redispatched[0]
        trace = router.request(fid).trace

        def trace_spans():
            spans = [span for span in router.obs.tracer.finished()
                     if span.trace_id == trace.trace_id]
            for backend in backends.values():
                data_backend, _ = open_backend(
                    os.path.join(backend._bucket_dir, "data"))
                spans += [span for span in read_spans(data_backend)
                          if span.trace_id == trace.trace_id]
            return spans

        assert wait_until(
            lambda: any(span.status == "exported"
                        for span in trace_spans())
            and any(span.name == "engine.decode" and span.status == "ok"
                    for span in trace_spans()),
            60, tick=fleet.tick, period=0.5), \
            "replica spans never reached the buckets"
        spans = trace_spans()
        names = {span.name for span in spans}
        assert {"request", "dispatch", "engine.queue", "engine.prefill",
                "engine.decode"} <= names
        dispatches = [span for span in spans if span.name == "dispatch"]
        assert len(dispatches) >= 2          # re-dispatch on the sibling
        assert {span.parent_id for span in dispatches} == {trace.span_id}
        assert len({span.source for span in spans
                    if span.name.startswith("engine.")}) >= 2
        waterfall = render_waterfall(spans)
        assert "engine.decode" in waterfall and "[exported]" in waterfall
        blob = json.dumps(chrome_trace(spans))   # valid Chrome-trace JSON
        events = json.loads(blob)["traceEvents"]
        assert events and all(event["ph"] == "X" for event in events)
    finally:
        # Stop the replica processes BEFORE deleting: task teardown
        # SIGKILLs only the agents' process groups, and the replicas run
        # in their own sessions (they also self-drain when orphaned, but
        # an explicit TERM makes teardown immediate and deterministic).
        import signal as signal_module

        for backend in backends.values():
            try:
                endpoint = json.load(open(os.path.join(
                    backend._bucket_dir, "data", "endpoint.json")))
                os.kill(int(endpoint.get("pid", 0)), signal_module.SIGTERM)
            except (OSError, ValueError):
                pass
        for backend in backends.values():
            try:
                backend.delete()
            except Exception:
                pass
