"""Chaos plane + heartbeat liveness, unit level.

Seeded fault injection (replayable per seed), the chaos transport against
``send``'s retry ladder, agent heartbeats / SIGTERM preemption handling, and
the TPU reconciler's liveness-requeue, requeue-backoff, and recovery-budget
paths — all hermetic. The end-to-end soak lives in ``test_chaos_soak.py``
(``make chaos``)."""

import io
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error

import pytest

from tpu_task.backends.tpu import api as tpu_api
from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    SPOT_ENABLED,
    Environment,
    Size,
    StatusCode,
    Task as TaskSpec,
)
from tpu_task.storage.http_util import send
from tpu_task.testing.chaos import (
    ChaosSchedule,
    ChaosTpuClient,
    ChaosTransport,
    flaky_storage,
)
from tpu_task import task as task_factory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- seeded schedule / replayability ------------------------------------------


def test_derived_streams_are_deterministic_and_independent():
    a, b = ChaosSchedule(seed=42), ChaosSchedule(seed=42)
    assert [a.derive("transport").random() for _ in range(5)] == \
        [b.derive("transport").random() for _ in range(5)]
    # Draw count at one seam never perturbs another seam's stream.
    noisy = ChaosSchedule(seed=42)
    for _ in range(100):
        noisy.derive("tpu-client").random()
    assert noisy.derive("transport").random() == \
        ChaosSchedule(seed=42).derive("transport").random()
    assert ChaosSchedule(seed=43).derive("transport").random() != \
        a.derive("transport").random()


def test_schedule_fires_timed_actions_and_retries_preconditions():
    clock = [0.0]
    schedule = ChaosSchedule(seed=1, now=lambda: clock[0])
    fired = []
    attempts = []

    def flaky_action():
        attempts.append(1)
        if len(attempts) < 2:
            return False  # precondition not met yet
        fired.append("done")
        return True

    schedule.at(1.0, flaky_action, label="x")
    schedule.tick()
    assert not attempts          # not due yet
    clock[0] = 1.2
    schedule.tick()
    assert attempts and not fired  # first try failed → retried later
    clock[0] = 2.0
    schedule.tick()
    assert fired == ["done"]
    clock[0] = 3.0
    schedule.tick()
    assert fired == ["done"]     # fires exactly once
    assert schedule.pending() == []


# -- control-plane seam --------------------------------------------------------


class _StubPlane:
    def __init__(self):
        self.calls = []

    def get_node(self, name):
        self.calls.append(("get_node", name))
        return tpu_api.NodeInfo(name=name, state="READY",
                                accelerator_type="v4-8")

    def preempt_node(self, name, graceful=False):
        self.calls.append(("preempt", name, graceful))


def test_chaos_tpu_client_injects_replayable_transient_errors():
    def run(seed):
        plane = _StubPlane()
        client = ChaosTpuClient(plane, ChaosSchedule(seed=seed),
                                error_rate=0.4)
        outcomes = []
        for _ in range(20):
            try:
                client.get_node("n")
                outcomes.append("ok")
            except urllib.error.HTTPError as error:
                outcomes.append(error.code)
        return outcomes

    first, second = run(9), run(9)
    assert first == second                       # replayable from the seed
    assert any(code in (429, 503) for code in first)
    assert "ok" in first
    assert run(10) != first


def test_chaos_tpu_client_scheduled_preempt_fires_through_inner_plane():
    clock = [0.0]
    plane = _StubPlane()
    schedule = ChaosSchedule(seed=3, now=lambda: clock[0])
    client = ChaosTpuClient(plane, schedule)
    client.preempt_at(2.0, "node-x")
    client.get_node("poll")      # tick at t=0: nothing due
    assert ("preempt", "node-x", False) not in plane.calls
    clock[0] = 2.5
    client.get_node("poll")      # tick fires the reclaim
    assert ("preempt", "node-x", False) in plane.calls
    assert [fault.kind for fault in schedule.injected] == ["preempt"]


# -- urlopen seam --------------------------------------------------------------


class _OkTransport:
    """Always answers 200 with a fixed body (the inner seam under chaos)."""

    def __init__(self, body=b"0123456789abcdef" * 8):
        self.body = body
        self.requests = []

    def __call__(self, request, timeout=None):
        self.requests.append(request)
        body = self.body

        class Response:
            headers = {}
            status = 200

            def read(self):
                return body

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

        return Response()


def test_chaos_transport_resets_and_timeouts_ride_the_retry_ladder():
    schedule = ChaosSchedule(seed=5)
    transport = ChaosTransport(schedule, inner=_OkTransport(),
                               reset_rate=0.3, timeout_rate=0.2)
    sleeps = []
    import random

    ok = 0
    for _ in range(10):
        body = send("GET", "http://x/y", urlopen=transport,
                    sleep=sleeps.append, rng=random.Random(0))
        ok += body is not None
    assert ok == 10                              # every request recovered
    kinds = {fault.kind for fault in schedule.injected}
    assert kinds & {"reset", "timeout"}          # chaos actually fired
    assert sleeps                                # ladder engaged


def test_chaos_transport_truncates_reads_and_fails_uploads():
    schedule = ChaosSchedule(seed=11)
    inner = _OkTransport()
    transport = ChaosTransport(schedule, inner=inner, truncate_rate=1.0)
    with transport(_request("GET", "http://x/y")) as response:
        assert len(response.read()) < len(inner.body)  # mid-stream drop

    schedule = ChaosSchedule(seed=11)
    transport = ChaosTransport(schedule, inner=_OkTransport(),
                               upload_fail_rate=1.0)
    with pytest.raises(urllib.error.HTTPError) as exc:
        transport(_request("PUT", "http://x/y", data=b"chunk"))
    assert exc.value.code == 503
    # Bodyless requests never draw the upload fault.
    transport(_request("GET", "http://x/y"))


def _request(method, url, data=None):
    import urllib.request

    return urllib.request.Request(url, data=data, method=method)


def test_flaky_storage_wraps_open_backend(tmp_path):
    from tpu_task.storage.backends import open_backend

    (tmp_path / "blob").write_bytes(b"x")
    schedule = ChaosSchedule(seed=2)
    with flaky_storage(schedule, fail_rate=1.0):
        from tpu_task.storage import backends as backends_module

        backend, _ = backends_module.open_backend(str(tmp_path))
        with pytest.raises(OSError, match="chaos"):
            backend.read("blob")
    backend, _ = open_backend(str(tmp_path))   # unpatched again
    assert backend.read("blob") == b"x"


# -- agent: heartbeats, SIGTERM preemption, log-loop resilience ----------------


def _agent_command(tmp_path, script_text, machine_id="m1", extra=()):
    remote = tmp_path / "bucket"
    workdir = tmp_path / "workdir"
    remote.mkdir(exist_ok=True)
    workdir.mkdir(exist_ok=True)
    script = tmp_path / "task.sh"
    script.write_text(script_text)
    command = [
        sys.executable, "-m", "tpu_task.machine.local_agent",
        "--remote", str(remote), "--directory", str(workdir),
        "--script", str(script), "--machine-id", machine_id,
        "--log-period", "0.1", "--data-period", "0.1",
        "--heartbeat-period", "0.1", *extra,
    ]
    return remote, workdir, command


def test_agent_writes_heartbeats_with_node_identity(tmp_path):
    remote, _workdir, command = _agent_command(
        tmp_path, "sleep 0.5\n", extra=("--node-name", "tpi-x-0"))
    process = subprocess.run(command, capture_output=True, text=True,
                             timeout=60, env={**os.environ, "PYTHONPATH": REPO})
    assert process.returncode == 0, process.stderr
    payload = json.loads((remote / "reports" / "heartbeat-m1").read_text())
    assert payload["machine"] == "m1"
    assert payload["node"] == "tpi-x-0"
    assert payload["worker"] == 0
    assert payload["final"] is True          # clean exit → final heartbeat


def test_agent_exports_node_identity_to_task(tmp_path):
    remote, _workdir, command = _agent_command(
        tmp_path, 'echo "node=$TPU_TASK_NODE"\n',
        extra=("--node-name", "tpi-x-3"))
    process = subprocess.run(command, capture_output=True, text=True,
                             timeout=60, env={**os.environ, "PYTHONPATH": REPO})
    assert process.returncode == 0, process.stderr
    assert "node=tpi-x-3" in (remote / "reports" / "task-m1").read_text()


def test_agent_sigterm_is_a_preemption_notice(tmp_path):
    """SIGTERM → child stopped, final data/log sync runs, terminal status
    report result "preempted" lands, NO self-destruct marker (the slice must
    be requeued, not torn down)."""
    remote, _workdir, command = _agent_command(
        tmp_path,
        "echo started\n"
        "echo progress > state.txt\n"
        "sleep 300\n")
    process = subprocess.Popen(command, env={**os.environ, "PYTHONPATH": REPO},
                               stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            if (remote / "reports" / "task-m1").exists() and \
                    "started" in (remote / "reports" / "task-m1").read_text():
                break
            time.sleep(0.1)
        else:
            raise AssertionError("agent never started the task")
        process.send_signal(signal.SIGTERM)
        process.wait(timeout=30)
    finally:
        if process.poll() is None:
            process.kill()
            process.wait()
    status = json.loads((remote / "reports" / "status-m1").read_text())
    assert status["result"] == "preempted"
    assert status["code"] == ""
    # Status folding counts a preempted report as neither success nor failure.
    from tpu_task.storage.sync import status as fold_status

    folded = fold_status(str(remote))
    assert folded.get(StatusCode.SUCCEEDED, 0) == 0
    assert folded.get(StatusCode.FAILED, 0) == 0
    # The preempted worker's last state still landed in the bucket.
    assert (remote / "data" / "state.txt").read_text() == "progress\n"
    assert not (remote / "shutdown").exists()
    # Graceful exit: final heartbeat, so liveness never flags this machine.
    assert json.loads(
        (remote / "reports" / "heartbeat-m1").read_text())["final"] is True


def test_log_loop_survives_transient_sync_errors(tmp_path):
    """One failed log sync must not kill log streaming for the rest of the
    run (the _data_loop contract, now shared)."""
    from tpu_task.machine.local_agent import Agent

    agent = Agent(remote=str(tmp_path / "bucket"),
                  directory=str(tmp_path / "work"), script_path="unused",
                  machine_id="m9", timeout_epoch=0,
                  log_period=0.02, data_period=999)
    failures = [2]  # fail the first two sync attempts
    real_sync = agent._sync_logs

    def flaky_sync():
        if failures[0] > 0:
            failures[0] -= 1
            raise OSError("chaos: bucket unavailable")
        real_sync()

    agent._sync_logs = flaky_sync
    agent._append_log("line-1\n")
    import threading

    thread = threading.Thread(target=agent._log_loop, daemon=True)
    thread.start()
    deadline = time.time() + 10
    report = tmp_path / "bucket" / "reports" / "task-m9"
    while time.time() < deadline and not report.exists():
        time.sleep(0.02)
    agent._done.set()
    thread.join(timeout=5)
    content = report.read_text()
    assert "line-1" in content
    assert "log sync error" in content   # the failures were recorded, not fatal


def test_sigterm_after_child_exit_keeps_real_result(tmp_path):
    """A teardown SIGTERM that lands AFTER the task finished must not
    relabel the run "preempted" — the terminal path reports the child's
    real result (the self-destruct scale-in race)."""
    from tpu_task.machine.local_agent import Agent

    agent = Agent(remote=str(tmp_path / "bucket"),
                  directory=str(tmp_path / "work"), script_path="unused",
                  machine_id="m1", timeout_epoch=0,
                  log_period=1, data_period=1)

    class FinishedChild:
        pid = 2 ** 22  # never a live pid in the test sandbox

        def poll(self):
            return 0

    old = signal.getsignal(signal.SIGTERM)
    try:
        agent._install_preemption_handler(FinishedChild())
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 2
        while time.time() < deadline and \
                signal.getsignal(signal.SIGTERM) is old:
            time.sleep(0.01)  # let the signal deliver
        assert not agent._preempted.is_set()
    finally:
        signal.signal(signal.SIGTERM, old)


def test_self_destruct_scale_in_is_graceful(tmp_path):
    """Self-destruct scale-in SIGTERMs surviving siblings: a still-running
    worker final-syncs and leaves a terminal status report instead of being
    SIGKILLed report-less (its last state would otherwise vanish)."""
    from tpu_task.backends.local.control_plane import MachineGroup

    group = MachineGroup("graceful-test", root=str(tmp_path / "cp"))
    script = (
        "#!/bin/bash\n"
        'if test "$TPU_WORKER_ID" = "0"; then echo lead done; exit 0; fi\n'
        "echo follower waiting\nsleep 300\n"
    )
    group.create(script, parallelism=2, timeout_epoch=0, environment={},
                 log_period=0.1, data_period=0.1)
    group.scale(2)
    try:
        # Worker 0 exits fast and writes the shutdown marker; reconcile then
        # scales to 0, gracefully terminating worker 1.
        deadline = time.time() + 30
        while time.time() < deadline:
            state = group.reconcile()
            if state.desired == 0 and not group.live_workers():
                break
            time.sleep(0.2)
        reports_dir = os.path.join(group.bucket, "reports")

        def statuses():
            return {name: json.loads(open(os.path.join(reports_dir, name)).read())
                    for name in os.listdir(reports_dir)
                    if name.startswith("status-")}

        deadline = time.time() + 15
        while time.time() < deadline and len(statuses()) < 2:
            time.sleep(0.2)  # the TERMed follower is still final-syncing
        reports = statuses()
        assert len(reports) == 2, f"a worker died report-less: {reports}"
        results = sorted(r["result"] for r in reports.values())
        assert results == ["preempted", "success"], results
    finally:
        group.delete()


# -- reconciler: liveness requeue, backoff, recovery budget --------------------


@pytest.fixture
def tpu_cloud(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_HEARTBEAT_PERIOD", "0.1")
    return Cloud(provider=Provider.TPU, region="us-central2")


def poll(condition, timeout=30.0, period=0.1, message="condition not reached"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if condition():
            return
        time.sleep(period)
    raise AssertionError(message)


def _make_task(tpu_cloud, name, script="#!/bin/bash\nsleep 300\n",
               run_workers=True):
    spec = TaskSpec(size=Size(machine="v4-8"),
                    environment=Environment(script=script), spot=SPOT_ENABLED)
    task = task_factory.new(tpu_cloud, Identifier.deterministic(name), spec)
    task.client.run_workers = run_workers
    return task


def _wait_active(task, qr_name, timeout=30.0):
    poll(lambda: task.client.get_queued_resource(qr_name).state
         == tpu_api.QR_ACTIVE, timeout=timeout,
         message=f"{qr_name} never went ACTIVE")


def test_liveness_requeues_hung_but_active_slice(tpu_cloud, monkeypatch):
    """Agent killed without the control plane noticing (node stays READY,
    QR stays ACTIVE): the stale heartbeat alone must get the slice requeued,
    with a durable liveness-requeue event for the MTTR record."""
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "0.8")
    monkeypatch.setenv("TPU_TASK_LIVENESS_BOOT_GRACE", "60")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0")
    task = _make_task(tpu_cloud, "liveness-hang")
    task.create()
    qr = task._qr_name(0)
    try:
        _wait_active(task, qr)
        heartbeat_dir = os.path.join(task._bucket_dir, "reports")
        poll(lambda: any(name.startswith("heartbeat-")
                         for name in os.listdir(heartbeat_dir))
             if os.path.isdir(heartbeat_dir) else False,
             message="no heartbeat ever reached the bucket")

        # Hang the worker: kill the agent directly; the node record still
        # says READY, so only the liveness layer can see this failure.
        node = json.loads(open(task.client._node_path(qr)).read())
        for worker in node["workers"]:
            os.killpg(worker["pid"], signal.SIGKILL)

        dead_blobs = {name for name in os.listdir(heartbeat_dir)
                      if name.startswith("heartbeat-")}

        def requeued():
            task.read()
            return "liveness-requeue" in [e.code for e in task.events()]

        poll(requeued, timeout=30, message="hung slice never requeued")
        # The requeue went through the control plane: the QR is alive again.
        assert task.client.get_queued_resource(qr).state in (
            tpu_api.QR_WAITING, tpu_api.QR_PROVISIONING, tpu_api.QR_ACTIVE)
        # The dead incarnation's heartbeat blobs were pruned: a FRESH
        # observer must read "no heartbeat yet" (boot grace), not a stale
        # blob it would spuriously requeue the booting replacement over.
        left = {name for name in os.listdir(heartbeat_dir)
                if name.startswith("heartbeat-")}
        assert not (dead_blobs & left), f"stale heartbeats survived: {left}"
        # Durable: a fresh observer sees the liveness decision from the
        # bucket mailbox with an MTTR-computable stamp.
        observer = task_factory.new(tpu_cloud,
                                    Identifier.deterministic("liveness-hang"),
                                    TaskSpec())
        events = [e for e in observer.events() if e.code == "liveness-requeue"]
        assert events and events[0].time.tzinfo is not None
    finally:
        task.delete()


def test_requeue_backoff_delays_consecutive_recoveries(tpu_cloud, monkeypatch):
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "0")  # liveness off
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "60")
    task = _make_task(tpu_cloud, "backoff", run_workers=False)
    task.create()
    qr = task._qr_name(0)
    try:
        _wait_active(task, qr)
        task.client.preempt_node(qr)
        task.read()              # first recovery: immediate
        assert task._requeue_state[qr]["attempts"] == 1
        _wait_active(task, qr)
        task.client.preempt_node(qr)
        for _ in range(3):
            task.read()          # inside the 60 s backoff window
        # Still SUSPENDED: the governor refused to thrash.
        assert task.client.get_queued_resource(qr).state == tpu_api.QR_SUSPENDED
        assert task._requeue_state[qr]["attempts"] == 1
    finally:
        task.delete()


def test_recovery_budget_exhaustion_converges_to_failed(tpu_cloud, monkeypatch):
    """A poisoned spec that re-suspends immediately N times must surface
    FAILED with the budget-exhausted event — and release the queued
    resource — instead of requeueing forever."""
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "0")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0")
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "2")
    monkeypatch.setenv("TPU_TASK_RECOVERY_HEALTHY_AFTER", "999")
    task = _make_task(tpu_cloud, "budget", run_workers=False)
    task.create()
    qr = task._qr_name(0)
    try:
        for _ in range(2):       # burn the whole budget
            _wait_active(task, qr)
            task.client.preempt_node(qr)
            task.read()
        _wait_active(task, qr)
        task.client.preempt_node(qr)
        task.read()              # budget exhausted → FAILED
        codes = [event.code for event in task.events()]
        assert "recovery-budget-exhausted" in codes
        assert task.status().get(StatusCode.FAILED, 0) >= 1
        assert qr not in task.client.list_queued_resources()
        # Latch: further reads don't try to recover a slice that is gone.
        task.read()
    finally:
        task.delete()


def test_healthy_requeue_resets_recovery_budget(tpu_cloud, monkeypatch):
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "0")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0")
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "2")
    monkeypatch.setenv("TPU_TASK_RECOVERY_HEALTHY_AFTER", "0.2")
    task = _make_task(tpu_cloud, "budget-reset", run_workers=False)
    task.create()
    qr = task._qr_name(0)
    try:
        _wait_active(task, qr)
        task.client.preempt_node(qr)
        task.read()
        assert task._requeue_state[qr]["attempts"] == 1
        _wait_active(task, qr)
        time.sleep(0.3)          # healthy uptime beyond HEALTHY_AFTER
        task.read()              # reset fires on the healthy observation
        assert task._requeue_state[qr]["attempts"] == 0
        # The budget now bounds CONSECUTIVE failures only: two more
        # recoveries fit without tripping FAILED.
        for _ in range(2):
            _wait_active(task, qr)
            task.client.preempt_node(qr)
            task.read()
        codes = [event.code for event in task.events()]
        assert "recovery-budget-exhausted" not in codes
    finally:
        task.delete()


def test_deleted_then_recreated_task_starts_with_fresh_budget(tpu_cloud,
                                                              monkeypatch):
    """The governor record must die with the slice: delete()/budget
    exhaustion prune `_requeue_state` (the heartbeat cache already prunes
    dead incarnations), so a deleted-then-recreated task gets a FRESH
    recovery budget instead of inheriting a latched `exhausted` — the leak
    also grew the dict forever under task churn."""
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "0")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0")
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "2")
    monkeypatch.setenv("TPU_TASK_RECOVERY_HEALTHY_AFTER", "999")
    task = _make_task(tpu_cloud, "budget-prune", run_workers=False)
    task.create()
    qr = task._qr_name(0)
    try:
        for _ in range(3):       # burn the budget, then trip exhaustion
            _wait_active(task, qr)
            task.client.preempt_node(qr)
            task.read()
        assert qr not in task.client.list_queued_resources()
        # Exhaustion released the slice AND its governor record.
        assert qr not in task._requeue_state
        assert qr not in task._first_active

        # Same task object, new life: delete + create must start clean.
        task.delete()
        assert task._requeue_state == {}
        assert task._first_active == {}
        task.create()
        _wait_active(task, qr)
        task.client.preempt_node(qr)
        task.read()
        # Fresh budget: attempt 1 of 2, no inherited latch — and the
        # requeue actually went through the control plane again.
        assert task._requeue_state[qr]["attempts"] == 1
        assert not task._requeue_state[qr]["exhausted"]
        _wait_active(task, qr)
    finally:
        task.delete()
