"""GCP / K8s / AWS / Azure backends: size grammars, validators, manifest
rendering, and hermetic lifecycle through the shared scaling-group plane."""

import json
import time

import pytest

from tpu_task import task as task_factory
from tpu_task.backends.aws import (
    resolve_aws_machine,
    resolve_aws_region,
    validate_instance_profile_arn,
)
from tpu_task.backends.az import resolve_az_machine, resolve_az_region, validate_arm_id
from tpu_task.backends.gcp import parse_gcp_machine, resolve_gcp_zone
from tpu_task.backends.k8s import parse_k8s_machine, render_manifests
from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    Environment,
    Size,
    StatusCode,
    Task as TaskSpec,
)

# --- grammars ---------------------------------------------------------------

def test_gcp_machine_grammar():
    m = parse_gcp_machine("m+v100")
    assert m.machine_type == "custom-8-65536-ext"
    assert m.accelerator_type == "nvidia-tesla-v100"
    assert m.accelerator_count == 1
    assert parse_gcp_machine("m").machine_type == "e2-custom-8-32768"
    assert parse_gcp_machine("n1-standard-4+nvidia-tesla-t4*2").accelerator_count == 2
    with pytest.raises(ValueError):
        parse_gcp_machine("bad+grammar*0")


def test_gcp_zone_resolution():
    assert resolve_gcp_zone("us-west") == "us-west1-b"
    assert resolve_gcp_zone("europe-west4-a") == "europe-west4-a"
    with pytest.raises(ValueError):
        resolve_gcp_zone("nowhere")


def test_aws_machine_and_region():
    assert resolve_aws_machine("m") == "m5.2xlarge"
    assert resolve_aws_machine("m+v100") == "p3.xlarge"
    assert resolve_aws_machine("g5.xlarge") == "g5.xlarge"
    with pytest.raises(ValueError):
        resolve_aws_machine("not a type")
    assert resolve_aws_region("us-east") == "us-east-1"
    assert resolve_aws_region("ap-southeast-2") == "ap-southeast-2"
    with pytest.raises(ValueError):
        resolve_aws_region("moon")


def test_aws_arn_validation():
    validate_instance_profile_arn("")
    validate_instance_profile_arn(
        "arn:aws:iam::123456789012:instance-profile/my-profile")
    with pytest.raises(ValueError):
        validate_instance_profile_arn("arn:aws:iam::12:role/x")


def test_az_machine_region_arm():
    assert resolve_az_machine("l+v100") == "Standard_NC12s_v3"
    assert resolve_az_region("eu-west") == "westeurope"
    validate_arm_id("")
    good = ("/subscriptions/12345678-1234-1234-1234-123456789abc"
            "/resourceGroups/rg/providers/Microsoft.ManagedIdentity"
            "/userAssignedIdentities/uid")
    assert validate_arm_id(good + "," + good) == [good, good]
    with pytest.raises(ValueError):
        validate_arm_id("/subscriptions/nope")


def test_k8s_machine_grammar():
    r = parse_k8s_machine("m+v100")
    assert (r.cpu, r.memory_mb, r.accelerator, r.gpu_count) == (8, 64000, "nvidia", 1)
    assert r.limits()["nvidia.com/gpu"] == "1"
    assert r.node_selector() == {"accelerator": "nvidia"}
    plain = parse_k8s_machine("m")
    assert plain.limits() == {"cpu": "8", "memory": "32000M"}
    with pytest.raises(ValueError):
        parse_k8s_machine("eight-lots")


# --- k8s manifests ----------------------------------------------------------

def test_k8s_manifests_indexed_job():
    spec = TaskSpec(size=Size(machine="m+t4", storage=30),
                    environment=Environment(script="#!/bin/sh\necho hi\n"),
                    parallelism=3)
    config_map, pvc, job = render_manifests(
        "tpi-test-3z4xlzwq-3u0vweb4", spec, region="disktype=ssd,zone=a")
    assert config_map["data"]["script"].startswith("#!/bin/sh")
    assert pvc["spec"]["accessModes"] == ["ReadWriteMany"]
    js = job["spec"]
    assert js["parallelism"] == js["completions"] == 3
    assert js["completionMode"] == "Indexed"
    assert js["backoffLimit"] == 2147483647
    assert js["activeDeadlineSeconds"] == 24 * 3600
    pod = js["template"]["spec"]
    assert pod["nodeSelector"] == {"disktype": "ssd", "zone": "a",
                                   "accelerator": "nvidia"}
    limits = pod["containers"][0]["resources"]["limits"]
    assert limits == {"cpu": "4", "memory": "16000M",
                      "ephemeral-storage": "30G", "nvidia.com/gpu": "1"}


def test_k8s_manifests_single_pod():
    spec = TaskSpec(environment=Environment(script="x", timeout=None))
    _, pvc, job = render_manifests("tpi-a-b-c", spec)
    assert pvc["spec"]["accessModes"] == ["ReadWriteOnce"]
    assert "storageClassName" not in pvc["spec"]  # cluster default applies
    assert "completionMode" not in job["spec"]
    assert "activeDeadlineSeconds" not in job["spec"]
    pod = job["spec"]["template"]["spec"]
    assert "serviceAccountName" not in pod  # no permission_set given


def test_k8s_workdir_grammar():
    from tpu_task.backends.k8s.manifests import parse_workdir

    parsed = parse_workdir("fast-ssd:20:/data/work")
    assert (parsed.storage_class, parsed.size_gb, parsed.path) == \
        ("fast-ssd", 20, "/data/work")
    parsed = parse_workdir("fast-ssd:/data/work")
    assert (parsed.storage_class, parsed.size_gb, parsed.path) == \
        ("fast-ssd", None, "/data/work")
    parsed = parse_workdir("/plain/path")
    assert (parsed.storage_class, parsed.size_gb, parsed.path) == \
        ("", None, "/plain/path")
    assert parse_workdir("").path == ""


def test_k8s_manifests_storage_class_and_size_override():
    spec = TaskSpec(size=Size(storage=30),
                    environment=Environment(script="x",
                                            directory="fast-ssd:20:/data/w"))
    _, pvc, _ = render_manifests("tpi-a-b-c", spec)
    assert pvc["spec"]["storageClassName"] == "fast-ssd"
    assert pvc["spec"]["resources"]["requests"]["storage"] == "20Gi"
    # Without the size segment, the task's disk size applies.
    spec.environment.directory = "fast-ssd:/data/w"
    _, pvc, _ = render_manifests("tpi-a-b-c", spec)
    assert pvc["spec"]["resources"]["requests"]["storage"] == "30Gi"


def test_k8s_manifests_service_account():
    spec = TaskSpec(environment=Environment(script="x"),
                    permission_set="train-sa")
    *_, job = render_manifests("tpi-a-b-c", spec,
                               automount_service_account_token=True)
    pod = job["spec"]["template"]["spec"]
    assert pod["serviceAccountName"] == "train-sa"
    assert pod["automountServiceAccountToken"] is True


def test_k8s_manifests_preallocated_claim():
    from tpu_task.common.values import RemoteStorage

    spec = TaskSpec(environment=Environment(script="x", directory="/w"),
                    remote_storage=RemoteStorage(container="shared",
                                                 path="/tasks/a/"))
    manifests = render_manifests("tpi-a-b-c", spec)
    assert [m["kind"] for m in manifests] == ["ConfigMap", "Job"]  # no PVC
    pod = manifests[-1]["spec"]["template"]["spec"]
    volume = next(v for v in pod["volumes"] if v["name"] == "workdir")
    assert volume["persistentVolumeClaim"]["claimName"] == "shared"
    mount = next(m for m in pod["containers"][0]["volumeMounts"]
                 if m["name"] == "workdir")
    assert mount["subPath"] == "tasks/a"


# --- hermetic lifecycle through each backend --------------------------------

@pytest.fixture
def hermetic(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_TASK_LOCAL_ROOT", str(tmp_path / "groups"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    monkeypatch.delenv("KUBECONFIG", raising=False)
    monkeypatch.delenv("KUBECONFIG_DATA", raising=False)
    return tmp_path


def poll(task, predicate, timeout=30.0, period=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        task.read()
        if predicate(task):
            return
        time.sleep(period)
    raise AssertionError(f"not reached; status={task.status()} logs={task.logs()}")


@pytest.mark.parametrize("provider,machine,region", [
    ("gcp", "m", "us-west"),
    ("k8s", "m", ""),
    ("aws", "m", "us-east"),
    ("az", "m", "us-west"),
])
def test_backend_lifecycle(hermetic, provider, machine, region):
    cloud = Cloud(provider=Provider(provider), region=region)
    spec = TaskSpec(
        size=Size(machine=machine),
        environment=Environment(
            script="#!/bin/bash\necho backend=$TPU_TASK_CLOUD_PROVIDER\n"),
    )
    identifier = Identifier.deterministic(f"{provider}-lc")
    task = task_factory.new(cloud, identifier, spec)
    task.delete()
    task.create()
    task.create()  # idempotent
    try:
        assert identifier in task_factory.list_tasks(cloud)
        poll(task, lambda t: t.status().get(StatusCode.SUCCEEDED, 0) >= 1)
        assert f"backend={provider}" in "".join(task.logs())
    finally:
        task.delete()
    assert identifier not in task_factory.list_tasks(cloud)


def test_gcp_tpu_machine_routes_to_tpu_backend(tmp_path, monkeypatch):
    """cloud=gcp machine=v4-8 provisions via the Cloud TPU control plane —
    the north-star retarget (BASELINE.json)."""
    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    from tpu_task.backends.tpu import TPUTask

    cloud = Cloud(provider=Provider.GCP, region="us-central2")
    spec = TaskSpec(size=Size(machine="v4-8"),
                    environment=Environment(script="#!/bin/bash\ntrue\n"))
    task = task_factory.new(cloud, Identifier.deterministic("gcp-tpu"), spec)
    assert isinstance(task, TPUTask)


def test_gcp_rejects_spot_bid(hermetic):
    from tpu_task.common.values import Spot

    cloud = Cloud(provider=Provider.GCP, region="us-west")
    spec = TaskSpec(size=Size(machine="m"), spot=Spot(0.5))
    with pytest.raises(ValueError, match="bidding"):
        task_factory.new(cloud, Identifier.deterministic("gcp-spot"), spec)
