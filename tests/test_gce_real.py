"""Real-mode GCE backend against a scripted compute REST transport.

Covers VERDICT r2 ask #3: InstanceTemplate + MIG via compute.googleapis.com,
the 6-rule firewall scheme, the ``{user}@{project}/{image-or-family}``
grammar with family fallback, and Size.storage honored as boot-disk size.
Reference: /root/reference/task/gcp/task.go,
task/gcp/resources/resource_instance_template.go,
resource_instance_group_manager.go, resource_firewall_rule.go,
data_source_image.go.
"""

import json

import pytest

from test_http_resilience import FakeSleep, FakeTransport

from tpu_task.backends.gcp.api import RestComputeClient, parse_permission_set
from tpu_task.backends.gcp.machines import parse_gcp_machine
from tpu_task.common.cloud import Cloud, Credentials, GCPCredentials, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    Environment, Firewall, FirewallRule as FirewallRuleSpec, Size, Spot,
    Task as TaskSpec,
)

CREDS = json.dumps({"project_id": "proj", "client_email": "sa@proj",
                    "private_key": "unused-in-tests"})


def _client(transport):
    client = RestComputeClient("proj", "us-west1-b")
    client._token._fetch = lambda: ("tok", 3600.0)
    client._urlopen = transport
    client._sleep = FakeSleep()
    return client


def _cloud():
    return Cloud(provider=Provider.GCP, region="us-west1-b",
                 credentials=Credentials(gcp=GCPCredentials(
                     application_credentials=CREDS)))


def _real_task(spec=None, transport=None):
    from tpu_task.backends.gcp.task import GCERealTask

    task = GCERealTask(_cloud(), Identifier.deterministic("gce"), spec or TaskSpec())
    task.client._token._fetch = lambda: ("tok", 3600.0)
    task.client._urlopen = transport
    task.client._sleep = FakeSleep()
    return task


# -- factory routing ----------------------------------------------------------


def test_factory_routes_to_real_gce_with_credentials(monkeypatch):
    from tpu_task.backends.gcp.task import GCERealTask, new_gcp_task

    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    task = new_gcp_task(_cloud(), Identifier.deterministic("t"), TaskSpec())
    assert isinstance(task, GCERealTask)


def test_factory_stays_hermetic_without_credentials(monkeypatch):
    from tpu_task.backends.gcp.task import GCPTask, new_gcp_task

    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    task = new_gcp_task(Cloud(provider=Provider.GCP, region="us-west1-b"),
                        Identifier.deterministic("t"), TaskSpec())
    assert isinstance(task, GCPTask)


def test_factory_fake_root_forces_hermetic(monkeypatch):
    from tpu_task.backends.gcp.task import GCPTask, new_gcp_task

    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", "/tmp/fake")
    task = new_gcp_task(_cloud(), Identifier.deterministic("t"), TaskSpec())
    assert isinstance(task, GCPTask)


# -- image grammar (data_source_image.go) -------------------------------------


def test_image_alias_and_direct_hit():
    from tpu_task.backends.gcp.resources import Image

    transport = FakeTransport([("ok", json.dumps({"selfLink": "lnk"}).encode())])
    image = Image(_client(transport), "")  # default → ubuntu alias
    image.read()
    assert image.ssh_user == "ubuntu"
    assert image.resource["selfLink"] == "lnk"
    assert "/projects/ubuntu-os-cloud/global/images/ubuntu-2004-lts" in \
        transport.requests[0].full_url


def test_image_family_fallback_on_404():
    from tpu_task.backends.gcp.resources import Image

    transport = FakeTransport([
        ("http", 404),  # direct image miss
        ("ok", json.dumps({"selfLink": "family-lnk"}).encode()),
    ])
    image = Image(_client(transport), "me@my-proj/my-family")
    image.read()
    assert image.ssh_user == "me"
    assert image.resource["selfLink"] == "family-lnk"
    assert "/projects/my-proj/global/images/family/my-family" in \
        transport.requests[1].full_url


def test_image_bad_grammar_raises():
    from tpu_task.backends.gcp.resources import Image

    with pytest.raises(ValueError, match="image"):
        Image(_client(FakeTransport([])), "no-at-sign/whatever").read()


# -- firewall scheme (gcp/task.go:72-128) -------------------------------------


def test_standard_firewall_rules_scheme():
    from tpu_task.backends.gcp.resources import standard_firewall_rules

    firewall = Firewall(ingress=FirewallRuleSpec(ports=[22, 80]))
    rules = standard_firewall_rules(_client(FakeTransport([])), "tpi-x",
                                    firewall, "net-link")
    names = [rule.name for rule in rules]
    assert names == ["tpi-x-e1", "tpi-x-i1", "tpi-x-e2", "tpi-x-i2",
                     "tpi-x-e3", "tpi-x-i3"]
    internal_egress = rules[0].body()
    assert internal_egress["destinationRanges"] == ["10.128.0.0/9"]
    assert internal_egress["priority"] == 1
    assert internal_egress["allowed"][0] == {"IPProtocol": "tcp"}  # every port
    user_ingress = rules[3].body()
    assert user_ingress["allowed"] == [
        {"IPProtocol": "tcp", "ports": ["22", "80"]},
        {"IPProtocol": "udp", "ports": ["22", "80"]}]
    assert "sourceRanges" not in user_ingress  # None nets → any (omitted)
    deny_ingress = rules[5].body()
    assert deny_ingress["denied"][0] == {"IPProtocol": "tcp"}
    assert deny_ingress["priority"] == 3
    assert deny_ingress["targetTags"] == ["tpi-x-i3"]


# -- instance template (resource_instance_template.go) ------------------------


def test_template_body_honors_disk_and_accelerator():
    from tpu_task.backends.gcp.resources import InstanceTemplate

    template = InstanceTemplate(
        _client(FakeTransport([])), "tpi-x", parse_gcp_machine("m+v100"),
        startup_script="#!/bin/sh\ntrue", ssh_public_key="ssh-rsa AAA",
        ssh_user="ubuntu", image_self_link="img", network_self_link="net",
        firewall_tags=["tpi-x-i2"], service_accounts=[{"email": "default"}],
        spot=0.0, disk_size_gb=200)
    body = template.body()
    props = body["properties"]
    assert props["machineType"] == "custom-8-65536-ext"
    assert props["guestAccelerators"] == [
        {"acceleratorType": "nvidia-tesla-v100", "acceleratorCount": 1}]
    assert props["disks"][0]["initializeParams"]["diskSizeGb"] == 200
    assert props["scheduling"] == {"onHostMaintenance": "TERMINATE",
                                   "preemptible": True}
    metadata = {item["key"]: item["value"] for item in props["metadata"]["items"]}
    assert metadata["startup-script"].startswith("#!/bin/sh")
    assert metadata["ssh-keys"] == "ubuntu:ssh-rsa AAA host\n"
    assert props["tags"]["items"] == ["tpi-x-i2"]


def test_template_spot_bid_rejected():
    from tpu_task.backends.gcp.resources import InstanceTemplate

    template = InstanceTemplate(
        _client(FakeTransport([])), "tpi-x", parse_gcp_machine("m"),
        startup_script="", ssh_public_key="", ssh_user="u",
        image_self_link="img", network_self_link="net", firewall_tags=[],
        service_accounts=[], spot=0.5)
    with pytest.raises(ValueError, match="bidding"):
        template.body()


def test_template_on_demand_migrates():
    from tpu_task.backends.gcp.resources import InstanceTemplate

    template = InstanceTemplate(
        _client(FakeTransport([])), "tpi-x", parse_gcp_machine("m"),
        startup_script="", ssh_public_key="k", ssh_user="u",
        image_self_link="img", network_self_link="net", firewall_tags=[],
        service_accounts=[], spot=-1.0)
    scheduling = template.body()["properties"]["scheduling"]
    assert scheduling == {"onHostMaintenance": "MIGRATE", "preemptible": False}


# -- permission set -----------------------------------------------------------


def test_permission_set_parsing():
    assert parse_permission_set("")[0]["email"] == "default"
    parsed = parse_permission_set(
        "sa@proj.iam.gserviceaccount.com,scopes=storage-rw,compute")
    assert parsed == [{"email": "sa@proj.iam.gserviceaccount.com",
                       "scopes": ["https://www.googleapis.com/auth/storage-rw",
                                  "https://www.googleapis.com/auth/compute"]}]
    with pytest.raises(ValueError):
        parse_permission_set("sa@x,bogus=1")


# -- lifecycle against scripted REST ------------------------------------------


def _done():
    return ("ok", json.dumps({"status": "DONE"}).encode())


@pytest.mark.slow
def test_create_issues_full_resource_plan(monkeypatch, tmp_path):
    spec = TaskSpec(size=Size(machine="m", storage=111),
                    environment=Environment(script="#!/bin/sh\ntrue"),
                    spot=Spot(-1))
    transport = FakeTransport([
        ("http", 404),  # recorded-remote probe: template doesn't exist yet
        ("ok", json.dumps({"selfLink": "net-link"}).encode()),   # network
        ("ok", json.dumps({"selfLink": "img-link"}).encode()),   # image
        _done(), _done(), _done(), _done(), _done(), _done(),    # 6 firewalls
        _done(),                                                  # template ins
        ("ok", json.dumps({"selfLink": "tpl-link"}).encode()),   # template get
        _done(),                                                  # MIG insert
        _done(),                                                  # resize
    ])
    task = _real_task(spec, transport)
    task.bucket.create = lambda: None  # GCS exercised in loopback tests
    monkeypatch.setattr("tpu_task.machine.wheel.stage_wheel", lambda remote: "")
    task.create()

    urls = [r.full_url for r in transport.requests]
    assert "/global/networks/default" in urls[1]
    assert sum("/global/firewalls" in u for u in urls) == 6
    template_insert = json.loads(transport.requests[9].data)
    assert template_insert["properties"]["disks"][0]["initializeParams"][
        "diskSizeGb"] == 111
    metadata_items = template_insert["properties"]["metadata"]["items"]
    assert metadata_items[1]["key"] == "startup-script"
    # The remote is recorded so bare read/delete target the right bucket.
    assert metadata_items[2]["key"] == "tpu-task-remote"
    assert task.identifier.long() in metadata_items[2]["value"]
    mig_insert = json.loads(transport.requests[11].data)
    assert mig_insert["instanceTemplate"] == "tpl-link"
    assert mig_insert["targetSize"] == 0
    assert urls[12].endswith("/resize?size=1")


def test_read_aggregates_addresses_status_events(monkeypatch):
    task = _real_task(TaskSpec())
    transport = FakeTransport([
        ("ok", json.dumps({"name": "mig"}).encode()),            # MIG get
        ("ok", json.dumps({"items": [{
            "timestamp": "2026-07-29T00:00:00Z",
            "error": {"code": "QUOTA", "message": "boom"},
            "instanceActionDetails": {"action": "CREATING"},
        }]}).encode()),                                          # listErrors
        ("ok", json.dumps({"items": [
            {"status": "RUNNING", "instance": "https://x/instances/vm-0"},
            {"status": "PROVISIONING", "instance": "https://x/instances/vm-1"},
        ]}).encode()),                                           # listInstances
        ("ok", json.dumps({"networkInterfaces": [{
            "accessConfigs": [{"natIP": "34.1.2.3"}]}]}).encode()),  # instance
        ("http", 404),  # recorded-remote probe (template gone → default)
    ])
    task.client._urlopen = transport
    monkeypatch.setattr("tpu_task.backends.gcs_remote.storage_status",
                        lambda remote, initial=None: initial)
    task.read()
    assert task.get_addresses() == ["34.1.2.3"]
    from tpu_task.common.values import StatusCode

    assert task.spec.status == {StatusCode.ACTIVE: 1}
    assert task.spec.events[0].code == "QUOTA"
    assert task.spec.events[0].description == ["boom", "CREATING"]


def test_delete_tolerates_missing_resources(monkeypatch):
    task = _real_task(TaskSpec())
    transport = FakeTransport([
        ("http", 404),  # recorded-remote probe
        ("http", 404),  # MIG delete
        ("http", 404),  # template delete
        ("http", 404), ("http", 404), ("http", 404),
        ("http", 404), ("http", 404), ("http", 404),  # 6 firewalls
    ])
    task.client._urlopen = transport
    task.bucket.delete = lambda: None
    task.delete()  # idempotent: no raise
    assert len(transport.requests) == 9


def test_stop_resizes_to_zero():
    task = _real_task(TaskSpec())
    transport = FakeTransport([_done()])
    task.client._urlopen = transport
    task.stop()
    assert transport.requests[0].full_url.endswith("/resize?size=0")


# -- TPU networkConfig / disk-size enforcement --------------------------------


def test_tpu_rejects_disk_size(monkeypatch, tmp_path):
    from tpu_task.backends.tpu.task import TPUTask

    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path))
    spec = TaskSpec(size=Size(machine="v4-8", storage=200))
    task = TPUTask(Cloud(provider=Provider.TPU, region="us-central2-b"),
                   Identifier.deterministic("t"), spec)
    with pytest.raises(ValueError, match="disk_size"):
        task.create()
    # Constructing (and tearing down) an existing task must keep working —
    # validation lives in create(), not __init__.
    task.stop()
    task.delete()


def test_tpu_external_ips_follow_firewall(monkeypatch, tmp_path):
    from tpu_task.backends.tpu.task import TPUTask

    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path))
    cloud = Cloud(provider=Provider.TPU, region="us-central2-b")

    open_spec = TaskSpec(size=Size(machine="v4-8"))
    task = TPUTask(cloud, Identifier.deterministic("t"), open_spec)
    assert task._qr_spec().enable_external_ips is True

    closed = TaskSpec(size=Size(machine="v4-8"),
                      firewall=Firewall(ingress=FirewallRuleSpec(ports=[])))
    task = TPUTask(cloud, Identifier.deterministic("t"), closed)
    assert task._qr_spec().enable_external_ips is False


def test_remote_storage_path_defaults_to_identifier():
    """Tasks sharing a pre-allocated container must not interleave at the
    container root: an empty path defaults to the identifier's short form
    (gcp/task.go:48-50)."""
    from tpu_task.common.values import RemoteStorage

    spec = TaskSpec(remote_storage=RemoteStorage(container="shared"))
    task = _real_task(spec)
    remote = task._remote()
    assert f":googlecloudstorage:shared/{task.identifier.short()}" == remote
    # Explicit paths pass through untouched.
    spec2 = TaskSpec(remote_storage=RemoteStorage(container="shared",
                                                  path="runs/7"))
    assert _real_task(spec2)._remote() == ":googlecloudstorage:shared/runs/7"


def test_bare_read_recovers_recorded_remote(monkeypatch, tmp_path):
    """A fresh task object with an empty TaskSpec (bare CLI read/delete) must
    target the storage the task was CREATED with — recovered from the queued
    resource's own metadata, not guessed from the default per-task bucket."""
    from tpu_task.backends.tpu.task import TPUTask
    from tpu_task.common.values import RemoteStorage

    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path))
    cloud = Cloud(provider=Provider.TPU, region="us-central2-b")
    identifier = Identifier.deterministic("bare-remote")
    created = TPUTask(cloud, identifier, TaskSpec(
        size=Size(machine="v4-8"),
        remote_storage=RemoteStorage(container="shared", path="runs/1")))
    created.start()  # submits queued resources whose metadata records the remote

    fresh = TPUTask(cloud, identifier, TaskSpec())
    assert fresh._remote() == ":googlecloudstorage:shared/runs/1"
    created.stop()
