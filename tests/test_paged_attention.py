"""Pallas paged-decode kernel + int8 KV blocks (interpret mode, CPU).

Three contract groups (docs/parity.md "Decode kernel + quantized KV"):

- **Kernel parity**: the block-table-walking kernel matches the XLA
  ``gather_kv`` + ``gqa_cached_attention`` reference within pinned
  tolerance over randomized block tables (fragmented, shared/refcounted,
  scratch sentinel), per-row positions, GQA group widths, and the
  ``spec_k + 1``-wide speculative shape — the same values through a
  different accumulation order (online softmax vs one dense rectangle).
- **int8 quantization**: per-(block, kv-head) symmetric round trip is
  bounded by scale/2 per element (property test); ``quantized_append``
  writes land at their offsets, zero garbage rows, and never touch
  un-written blocks.
- **Engine smokes** (tier-1 ``perf``): fp32 greedy streams through the
  interpret-mode kernel are identical to the XLA path's; the int8 engine
  reproduces the fp32 greedy stream on the pinned small config (the
  tolerance contract's stream-identity anchor); geometry validation is an
  actionable error / warned fallback, never a Pallas trace failure.
"""

import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpu_task.ml.models import transformer
from tpu_task.ml.ops import paged_attention as pa
from tpu_task.ml.ops.paged_attention import (
    kernel_constraint_violation,
    paged_decode_attention,
    paged_reference_attention,
)
from tpu_task.ml.serving import ServingConfig, ServingEngine
from tpu_task.ml.serving.cache import (
    INT8_SCALE_EPS,
    dequantize_blocks,
    quantize_blocks,
    quantized_append,
)

ATOL = 2e-5  # accumulation-order tolerance, same pin as the flash suite


def _random_case(rng, slots=4, w=1, h=4, kv=2, d=16, n_blocks=32, bs=8,
                 max_blocks=5, int8=False):
    """A deliberately nasty paged layout: tables draw blocks in scrambled
    (fragmented) order, two slots SHARE their first block (the prefix-cache
    shape), unallocated tails keep the scratch sentinel 0, and per-row
    positions put every slot at a different depth."""
    q = jnp.asarray(rng.normal(size=(slots, w, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, kv, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, kv, d)), jnp.float32)
    tables = np.zeros((slots, max_blocks), np.int32)
    perm = rng.permutation(np.arange(1, n_blocks))
    pos = np.zeros((slots, w), np.int32)
    used = 0
    for s in range(slots):
        depth = int(rng.integers(1, max_blocks * bs - w))
        n_full = (depth + w - 1) // bs + 1
        tables[s, :n_full] = perm[used:used + n_full]
        used += n_full
        pos[s] = depth + np.arange(w)
    tables[1, 0] = tables[0, 0]          # shared (refcounted) first block
    pos[-1, :] = np.arange(w)            # a fresh slot right at position 0
    ks = vs = None
    if int8:
        kp, ks = quantize_blocks(kp)
        vp, vs = quantize_blocks(vp)
    return q, kp, vp, jnp.asarray(tables), jnp.asarray(pos), ks, vs


@pytest.mark.parametrize("kv", [1, 2, 4])
@pytest.mark.parametrize("w", [1, 3])
def test_kernel_matches_gather_reference(kv, w):
    """Kernel vs the XLA gather+dense reference over randomized fragmented
    / shared / scratch-holding tables, per-row positions, GQA widths
    (kv=4 is MHA), and the multi-token (spec-shaped) width."""
    rng = np.random.default_rng(100 * kv + w)
    q, kp, vp, tables, pos, _, _ = _random_case(rng, kv=kv, w=w)
    out = paged_decode_attention(q, kp, vp, tables, pos, interpret=True)
    ref = paged_reference_attention(q, kp, vp, tables, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=ATOL)


def test_kernel_spec_shape_with_invalid_rows():
    """The k+1-wide speculative layout: invalid tail positions are zeroed
    by the engine (same contract as the XLA path) — outputs for them are
    garbage the host discards, but VALID rows must still be exact."""
    rng = np.random.default_rng(7)
    w = 4
    q, kp, vp, tables, pos, _, _ = _random_case(rng, w=w)
    pos = np.asarray(pos)
    valid = np.ones_like(pos, bool)
    valid[0, 2:] = False                  # slot 0 exhausted after 2
    valid[2, 1:] = False                  # slot 2 holds a bare re-score
    pos = jnp.asarray(np.where(valid, pos, 0))
    out = paged_decode_attention(q, kp, vp, tables, pos, interpret=True)
    ref = paged_reference_attention(q, kp, vp, tables, pos)
    np.testing.assert_allclose(
        np.asarray(out)[valid], np.asarray(ref)[valid], atol=ATOL)


def test_kernel_int8_matches_dequant_reference():
    """int8 pools: the kernel's in-register dequantization (scale factored
    out of both matmuls) vs the XLA gather→dequantize→dense reference —
    both read the SAME codes, so this is tight accumulation tolerance,
    not the quantization error."""
    rng = np.random.default_rng(11)
    q, kp, vp, tables, pos, ks, vs = _random_case(rng, w=2, int8=True)
    out = paged_decode_attention(q, kp, vp, tables, pos, ks, vs,
                                 interpret=True)
    ref = paged_reference_attention(q, kp, vp, tables, pos, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=ATOL)


# -- int8 quantization properties --------------------------------------------

def test_int8_round_trip_error_bound():
    """|dequant(quantize(x)) − x| ≤ scale/2 per element, across blocks of
    wildly mixed magnitudes (each block/head pair gets its own scale, so a
    hot block cannot poison a quiet one's precision)."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(16, 8, 4, 32)) * (
        10.0 ** rng.integers(-3, 3, size=(16, 1, 4, 1)))
    x = jnp.asarray(x, jnp.float32)
    codes, scale = quantize_blocks(x)
    err = np.abs(np.asarray(dequantize_blocks(codes, scale)) - np.asarray(x))
    bound = np.broadcast_to(
        np.asarray(scale)[:, None, :, None] / 2, err.shape)
    assert (err <= bound * (1 + 1e-6) + 1e-12).all()
    # The amax element maps to exactly ±127 — nothing clips.
    assert int(np.abs(np.asarray(codes)).max()) == 127
    # All-zero blocks stay exactly zero at the epsilon scale.
    z_codes, z_scale = quantize_blocks(jnp.zeros((2, 4, 2, 8)))
    assert not np.asarray(z_codes).any()
    np.testing.assert_allclose(np.asarray(z_scale), INT8_SCALE_EPS,
                               rtol=1e-6)


def test_quantized_append_writes_offsets_and_zeroes_garbage():
    """Append into a half-filled block: the new token lands at its offset
    within scale/2, earlier tokens survive requantization within the
    documented drift, rows past ``filled`` are zeroed, and blocks OUTSIDE
    ``touched`` keep their codes and scales bit-identical."""
    rng = np.random.default_rng(5)
    n, bs, kv, d = 6, 4, 2, 8
    base = jnp.asarray(rng.normal(size=(n, bs, kv, d)), jnp.float32)
    codes, scale = quantize_blocks(base)
    pool = {"k": codes, "k_scale": scale, "v": codes, "v_scale": scale}
    new = jnp.asarray(rng.normal(size=(1, kv, d)), jnp.float32)
    # Write one token at offset 2 of physical block 3: filled becomes 3.
    touched = jnp.asarray([3, 0], jnp.int32)   # + pad entry
    filled = jnp.asarray([3, 0], jnp.int32)
    wt = jnp.asarray([0], jnp.int32)
    wo = jnp.asarray([2], jnp.int32)
    out, qerr = quantized_append(pool, new, new, touched, filled, wt, wo)
    got = np.asarray(dequantize_blocks(out["k"], out["k_scale"]))
    s3 = float(np.asarray(out["k_scale"])[3].max())
    # The written token is exact to its block's new scale.
    assert np.abs(got[3, 2] - np.asarray(new)[0]).max() <= s3 / 2 + 1e-9
    # Garbage rows (>= filled) zeroed; earlier rows survive within drift.
    assert (got[3, 3:] == 0).all()
    old = np.asarray(dequantize_blocks(codes, scale))
    assert np.abs(got[3, :2] - old[3, :2]).max() <= s3 + 1e-9
    # Untouched blocks: codes AND scales bit-identical.
    keep = [1, 2, 4, 5]
    np.testing.assert_array_equal(
        np.asarray(out["k"])[keep], np.asarray(codes)[keep])
    np.testing.assert_array_equal(
        np.asarray(out["k_scale"])[keep], np.asarray(scale)[keep])
    assert float(qerr) <= s3 / 2 + 1e-9


# -- geometry validation / impl resolution ------------------------------------

def test_kernel_constraint_violation_reasons():
    assert kernel_constraint_violation(16, 128) is None
    assert "d_head" in kernel_constraint_violation(16, 96)
    assert "block_size" in kernel_constraint_violation(6, 128)
    # The sublane tile tracks the POOL element width: int8 pools (1 byte)
    # need block_size % 32, bf16 % 16 — fp32's % 8 is the loosest.
    assert "block_size" in kernel_constraint_violation(16, 128, 1)
    assert kernel_constraint_violation(32, 128, 1) is None
    assert kernel_constraint_violation(16, 128, 2) is None
    # And the engine resolver feeds the kv_dtype-aware width through.
    from tpu_task.ml.serving.engine import _kv_itemsize
    assert _kv_itemsize(ServingConfig(kv_dtype="int8"), TINY) == 1
    assert _kv_itemsize(ServingConfig(), TINY) == 4
    # Scalar-prefetch SMEM budget: a huge int8 pool's scale sidecars are
    # rejected even with perfect tiling (compiled path only).
    assert "SMEM" in kernel_constraint_violation(
        32, 128, 1, n_blocks=65536, kv_heads=8, slots=8, max_blocks=16,
        quantized=True)
    assert kernel_constraint_violation(
        32, 128, 1, n_blocks=512, kv_heads=2, slots=8, max_blocks=16,
        quantized=True) is None


def test_quantized_public_entry_requires_qa(params):
    """The exported step fns fail ACTIONABLY when handed int8 pools
    without the host-computed write layout, instead of an opaque
    TypeError from inside a traced closure."""
    from tpu_task.ml.serving.cache import init_pools
    from tpu_task.ml.serving.model import paged_decode_step

    scfg = ServingConfig(slots=2, block_size=4, n_blocks=8, max_len=16,
                         kv_dtype="int8")
    pools = init_pools(TINY, scfg)
    with pytest.raises(ValueError, match="qa"):
        paged_decode_step(
            transformer.init(jax.random.PRNGKey(0), TINY), TINY,
            jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.int32),
            jnp.zeros((2, 4), jnp.int32), jnp.ones((2,), bool), pools)


TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32, n_kv_heads=2)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def test_decode_impl_validation_and_fallback(params, monkeypatch):
    """Bad geometry under an explicit 'pallas' is an ACTIONABLE error (and
    off-TPU 'pallas' names the interpret alternative); under 'auto' on a
    TPU backend it warns once and falls back to XLA — recorded in stats,
    never a Pallas trace failure mid-decode."""
    with pytest.raises(ValueError, match="decode_impl"):
        ServingConfig(decode_impl="mosaic")
    with pytest.raises(ValueError, match="kv_dtype"):
        ServingConfig(kv_dtype="fp4")
    # CPU backend: explicit pallas points at interpret/xla.
    with pytest.raises(ValueError, match="interpret"):
        ServingEngine(params, TINY, ServingConfig(decode_impl="pallas"))
    # "TPU" backend (faked), geometry violating the lane tile (d_head=8):
    monkeypatch.setattr(pa, "use_pallas_paged", lambda: True)
    with pytest.raises(ValueError, match="d_head"):
        ServingEngine(params, TINY, ServingConfig(decode_impl="pallas"))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng = ServingEngine(params, TINY, ServingConfig())
    assert eng.decode_impl == "xla"
    assert eng.stats()["decode_impl"] == "xla"
    assert any("falling back" in str(w.message).lower()
               or "falls back" in str(w.message).lower() for w in caught)


def test_draft_geometry_falls_back_without_losing_target_kernel(monkeypatch):
    """Speculative decoding with a draft whose d_head violates the kernel
    tile constraints: the TARGET keeps the compiled kernel, the DRAFT
    programs fall back to XLA with a warning — construction never defers
    a Mosaic trace failure into the first speculative round."""
    monkeypatch.setattr(pa, "use_pallas_paged", lambda: True)
    target = transformer.TransformerConfig(
        vocab_size=64, d_model=256, n_layers=1, n_heads=2, d_head=128,
        d_ff=64, dtype=jnp.float32, n_kv_heads=2)
    draft = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, dtype=jnp.float32, n_kv_heads=2)
    scfg = ServingConfig(slots=2, block_size=8, n_blocks=16, max_len=64,
                         spec_k=2, prefix_cache=False)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng = ServingEngine(
            transformer.init(jax.random.PRNGKey(0), target), target, scfg,
            draft_params=transformer.init(jax.random.PRNGKey(1), draft),
            draft_cfg=draft)
    assert eng.decode_impl == "pallas"
    assert any("draft" in str(w.message).lower() for w in caught)


# -- engine smokes (tier-1 perf) ----------------------------------------------

def _drain(params, cfg, scfg, reqs):
    eng = ServingEngine(params, cfg, scfg)
    rids = [eng.submit(p, n) for p, n in reqs]
    out = eng.drain()
    assert eng.allocator.referenced == 0
    return [out[r] for r in rids], eng


@pytest.mark.perf
def test_engine_interpret_kernel_greedy_matches_xla(params):
    """Tier-1 kernel smoke: the engine's fused steps routed through the
    interpret-mode Pallas kernel produce the SAME greedy streams as the
    XLA gather path on a mixed-length workload (chunked prefill, slot
    reuse, lazy growth) — the kernel path exercised end to end on CPU."""
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, TINY.vocab_size, size=plen), new)
            for plen, new in [(5, 6), (9, 4), (3, 8), (14, 5)]]
    base_cfg = dict(slots=3, block_size=4, n_blocks=24, max_len=32,
                    chunk_tokens=6)
    xla, eng_x = _drain(params, TINY, ServingConfig(**base_cfg), reqs)
    krn, eng_k = _drain(
        params, TINY, ServingConfig(decode_impl="interpret", **base_cfg),
        reqs)
    assert xla == krn
    assert eng_x.stats()["decode_impl"] == "xla"
    assert eng_k.stats()["decode_impl"] == "interpret"


# GQA + d_head sized so int8 rounding does not flip any argmax on this
# seeded workload — the "greedy-stream-identity on small configs" anchor
# of the tolerance contract (docs/parity.md). Deterministic on CPU.
INT8_PIN = transformer.TransformerConfig(
    vocab_size=128, d_model=128, n_layers=2, n_heads=4, d_head=16,
    d_ff=256, dtype=jnp.float32, n_kv_heads=2)


@pytest.mark.perf
def test_engine_int8_greedy_stream_identity_small_config():
    """Tier-1 int8 smoke: the int8 engine reproduces the fp32 engine's
    greedy streams exactly on the pinned config, halves (here: quarters —
    fp32 model) the per-token KV bytes, and counts its block writes."""
    from tpu_task.ml.serving.cache import kv_token_bytes

    params = transformer.init(jax.random.PRNGKey(0), INT8_PIN)
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, INT8_PIN.vocab_size, size=plen), 8)
            for plen in (5, 11, 3)]
    base_cfg = dict(slots=3, block_size=4, n_blocks=32, max_len=48,
                    chunk_tokens=6, prefix_cache=False)
    fp, _ = _drain(params, INT8_PIN, ServingConfig(**base_cfg), reqs)
    i8, eng = _drain(params, INT8_PIN,
                     ServingConfig(kv_dtype="int8", **base_cfg), reqs)
    assert fp == i8
    st = eng.stats()
    assert st["kv_quant"]["kv_dtype"] == "int8"
    assert st["kv_quant"]["quantized_block_writes"] > 0
    fp_bytes = kv_token_bytes(INT8_PIN)
    assert st["kv_bytes_per_token"] < fp_bytes / 2
    # Pool bytes shrink accordingly (scale sidecars included).
    assert st["kv_pool_bytes"] < ServingEngine(
        params, INT8_PIN, ServingConfig(**base_cfg)
    ).stats()["kv_pool_bytes"] / 2


def test_engine_int8_interpret_matches_int8_xla():
    """The kernel's in-register dequantization agrees with the XLA
    dequantize-then-attend reference at the STREAM level too: both int8
    paths read the same codes, so greedy tokens match exactly."""
    params = transformer.init(jax.random.PRNGKey(0), INT8_PIN)
    rng = np.random.default_rng(1)
    reqs = [(rng.integers(0, INT8_PIN.vocab_size, size=plen), 6)
            for plen in (4, 9)]
    base_cfg = dict(slots=2, block_size=4, n_blocks=24, max_len=32,
                    chunk_tokens=5, prefix_cache=False, kv_dtype="int8")
    a, _ = _drain(params, INT8_PIN, ServingConfig(**base_cfg), reqs)
    b, _ = _drain(params, INT8_PIN,
                  ServingConfig(decode_impl="interpret", **base_cfg), reqs)
    assert a == b


def test_engine_int8_spec_and_cache_modes_drain():
    """int8 under the production modes: speculative decoding (the k+1-wide
    quantized write/score round) and the prefix cache + COW (scale
    sidecars copy with their blocks) both run to completion and produce
    full streams; stream CONTENT under these modes is tolerance-class,
    not pinned (requantization drift depends on write history)."""
    params = transformer.init(jax.random.PRNGKey(0), INT8_PIN)
    rng = np.random.default_rng(2)
    shared = rng.integers(0, INT8_PIN.vocab_size, size=9)
    prompts = [np.concatenate([shared,
                               rng.integers(0, INT8_PIN.vocab_size, size=3)])
               for _ in range(3)]
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=32, max_len=48,
                         chunk_tokens=6, kv_dtype="int8")
    eng = ServingEngine(params, INT8_PIN, scfg)
    rids = [eng.submit(p, 6) for p in prompts]
    out = eng.drain()
    assert all(len(out[r]) == 6 for r in rids)
    assert eng.stats()["prefix_cache"]["hit_requests"] >= 1

    draft = transformer.init(jax.random.PRNGKey(1), INT8_PIN)
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=32, max_len=48,
                         chunk_tokens=6, kv_dtype="int8", spec_k=2,
                         prefix_cache=False)
    eng = ServingEngine(params, INT8_PIN, scfg, draft_params=draft,
                        draft_cfg=INT8_PIN)
    rids = [eng.submit(p, 6) for p in prompts[:2]]
    out = eng.drain()
    assert all(len(out[r]) == 6 for r in rids)
    assert eng.stats()["spec"]["rounds"] > 0
    assert eng.allocator.referenced == 0


def test_engine_tp8_interpret_kernel_matches_single_chip():
    """The kernel under tensor parallelism: pools kv-head-sharded over a
    tp=8 mesh, the kernel running per shard under shard_map (kv-head axis
    local, no cross-shard reduction) — greedy streams identical to the
    single-chip XLA engine's."""
    from tpu_task.ml.parallel.mesh import make_mesh

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_head=8,
        d_ff=64, dtype=jnp.float32, n_kv_heads=8)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, cfg.vocab_size, size=plen), new)
            for plen, new in [(5, 4), (9, 3)]]
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=24, max_len=24,
                         chunk_tokens=5)
    single, _ = _drain(params, cfg, scfg, reqs)

    mesh = make_mesh(8, axis_names=("tp",), axis_sizes=(8,))
    eng = ServingEngine(
        params, cfg,
        ServingConfig(slots=2, block_size=4, n_blocks=24, max_len=24,
                      chunk_tokens=5, decode_impl="interpret"),
        mesh=mesh)
    rids = [eng.submit(p, n) for p, n in reqs]
    out = eng.drain()
    assert [out[r] for r in rids] == single


# -- fp8 (e4m3) quantized pools (PR 13) ---------------------------------------

def test_fp8_round_trip_error_bound():
    """fp8 e4m3 round trip mirrors the int8 property with a RELATIVE
    bound: |dequant(quantize(x)) − x| ≤ max(|x|·2⁻⁴, scale·2⁻⁹) per
    element (half-ulp of a 3-bit-mantissa normal; the subnormal step at
    the bottom), across blocks of wildly mixed magnitudes. Where int8's
    uniform grid loses small entries of an outlier-heavy block, fp8
    keeps them to relative precision."""
    from tpu_task.ml.serving.cache import FP8_MAX, fp8_supported

    if not fp8_supported():
        pytest.skip("no fp8 support in this jax build")
    rng = np.random.default_rng(7)
    x = rng.normal(size=(16, 8, 4, 32)) * (
        10.0 ** rng.integers(-3, 3, size=(16, 1, 4, 1)))
    x = jnp.asarray(x, jnp.float32)
    codes, scale = quantize_blocks(x, jnp.float8_e4m3fn)
    assert codes.dtype == jnp.dtype(jnp.float8_e4m3fn)
    err = np.abs(np.asarray(dequantize_blocks(codes, scale))
                 - np.asarray(x))
    s = np.broadcast_to(np.asarray(scale)[:, None, :, None], err.shape)
    bound = np.maximum(np.abs(np.asarray(x)) * 2.0 ** -4, s * 2.0 ** -9)
    assert (err <= bound * (1 + 1e-6) + 1e-12).all()
    # Nothing overflows: the amax element maps to exactly ±FP8_MAX.
    finite = np.isfinite(np.asarray(codes.astype(jnp.float32)))
    assert finite.all()
    assert float(np.abs(np.asarray(codes.astype(jnp.float32))).max()) \
        == FP8_MAX
    # Small-vs-large precision shape: a block mixing 1e-3s with a 100.0
    # outlier keeps the small entries nonzero at fp8 (within the
    # subnormal-step bound, ~4e-4 at this scale); int8's uniform grid
    # (scale ≈ 0.79) flattens them to exactly 0.
    mixed = jnp.full((1, 8, 1, 8), 1e-3, jnp.float32)
    mixed = mixed.at[0, 0, 0, 0].set(100.0)
    f8 = dequantize_blocks(*quantize_blocks(mixed, jnp.float8_e4m3fn))
    i8 = dequantize_blocks(*quantize_blocks(mixed))
    assert float(f8[0, 3, 0, 3]) > 0.0
    assert abs(float(f8[0, 3, 0, 3]) - 1e-3) < (100.0 / FP8_MAX) * 2 ** -9
    assert float(i8[0, 3, 0, 3]) == 0.0
    # All-zero blocks stay exactly zero at the epsilon scale.
    z_codes, _ = quantize_blocks(jnp.zeros((2, 4, 2, 8)),
                                 jnp.float8_e4m3fn)
    assert not np.asarray(z_codes.astype(jnp.float32)).any()


# -- DMA-pipelined kernel (PR 13) ---------------------------------------------

def test_pipelined_kernel_matches_gather_reference():
    """The double-buffered-DMA kernel vs the XLA gather+dense reference
    over the SAME randomized fragmented/shared/scratch tables the PR 9
    kernel is pinned on — fp32, int8, and fp8 pools, plain and
    spec-shaped widths. One tolerance class for both kernels: same
    values, different accumulation order."""
    from tpu_task.ml.ops.paged_attention import (
        paged_decode_pipelined_attention)
    from tpu_task.ml.serving.cache import fp8_supported

    rng = np.random.default_rng(23)
    for w in (1, 3):
        q, kp, vp, tables, pos, _, _ = _random_case(rng, w=w)
        out = paged_decode_pipelined_attention(q, kp, vp, tables, pos,
                                               interpret=True)
        ref = paged_reference_attention(q, kp, vp, tables, pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=ATOL)
    # Quantized pools: in-register dequantization through the same walk.
    q, kp, vp, tables, pos, ks, vs = _random_case(rng, w=2, int8=True)
    out = paged_decode_pipelined_attention(q, kp, vp, tables, pos, ks, vs,
                                           interpret=True)
    ref = paged_reference_attention(q, kp, vp, tables, pos, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=ATOL)
    if fp8_supported():
        q, kpf, vpf, tables, pos, _, _ = _random_case(rng, w=2)
        kpf, ksf = quantize_blocks(kpf, jnp.float8_e4m3fn)
        vpf, vsf = quantize_blocks(vpf, jnp.float8_e4m3fn)
        out = paged_decode_pipelined_attention(
            q, kpf, vpf, tables, pos, ksf, vsf, interpret=True)
        ref = paged_reference_attention(q, kpf, vpf, tables, pos, ksf, vsf)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=ATOL)


@pytest.mark.slow
def test_engine_interpret_pipelined_greedy_matches_xla(params):
    """The engine's fused steps routed through the interpret-mode
    PIPELINED kernel produce the same greedy streams as the XLA gather
    path — the decode_impl="interpret_pipelined" mode end to end,
    micro-steps included."""
    import dataclasses

    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, TINY.vocab_size, size=plen), new)
            for plen, new in [(5, 6), (9, 4), (3, 8)]]
    base_cfg = dict(slots=3, block_size=4, n_blocks=24, max_len=32,
                    chunk_tokens=6, micro_k=2)
    xla, _ = _drain(params, TINY, ServingConfig(**base_cfg), reqs)
    pipe, eng = _drain(
        params, TINY,
        ServingConfig(decode_impl="interpret_pipelined", **base_cfg),
        reqs)
    assert xla == pipe
    assert eng.stats()["decode_impl"] == "interpret_pipelined"


@pytest.mark.slow
def test_engine_fp8_greedy_stream_identity_small_config():
    """The fp8 analogue of the int8 anchor pin: the fp8 engine
    reproduces the fp32 engine's greedy streams exactly on the pinned
    small config, at the same per-token bytes as int8."""
    from tpu_task.ml.serving.cache import fp8_supported, kv_token_bytes

    if not fp8_supported():
        pytest.skip("no fp8 support in this jax build")
    params = transformer.init(jax.random.PRNGKey(0), INT8_PIN)
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, INT8_PIN.vocab_size, size=plen), 8)
            for plen in (5, 11, 3)]
    base_cfg = dict(slots=3, block_size=4, n_blocks=32, max_len=48,
                    chunk_tokens=6, prefix_cache=False)
    fp, _ = _drain(params, INT8_PIN, ServingConfig(**base_cfg), reqs)
    f8, eng = _drain(params, INT8_PIN,
                     ServingConfig(kv_dtype="fp8", **base_cfg), reqs)
    assert fp == f8
    st = eng.stats()
    assert st["kv_quant"]["kv_dtype"] == "fp8"
    assert st["kv_quant"]["quantized_block_writes"] > 0
    # Same bytes/token as int8 — fp8 trades error shape, not density.
    assert st["kv_bytes_per_token"] == kv_token_bytes(
        INT8_PIN, ServingConfig(kv_dtype="int8", **base_cfg))


def test_fp8_unsupported_backend_is_actionable(params, monkeypatch):
    """A backend without fp8 gets a construction-time error naming the
    gate and the alternatives — never a lowering failure mid-decode."""
    import tpu_task.ml.serving.engine as engine_mod

    monkeypatch.setattr(engine_mod, "fp8_supported", lambda: False)
    with pytest.raises(ValueError, match="fp8_supported"):
        ServingEngine(params, TINY, ServingConfig(kv_dtype="fp8"))
