"""Compiled-on-hardware regression tests for the training compute stack
beyond the attention kernels (those live in test_ops_attention.py).

``make kernels-tpu`` selects every ``compiled`` test across the suite; this
file pins the fused blockwise cross-entropy, MoE top-k routing, and the full
train step — the pieces the MFU headline runs — against their dense/XLA
ground truths ON THE CHIP, so a numerics regression in any of them fails
the hardware gate instead of silently drifting a bench number. Hermetic CPU
coverage of the same math lives in test_ml_models.py / test_ml_moe_pipeline.py;
hardware evidence must not silently fall back (guard fixture below).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REAL_TPU = bool(os.environ.get("TPU_TASK_TEST_REAL_TPU"))

on_tpu = pytest.mark.skipif(
    not REAL_TPU, reason="compiled-train tests need TPU_TASK_TEST_REAL_TPU=1")


@pytest.fixture(autouse=True)
def _no_silent_cpu_fallback(request):
    if REAL_TPU and request.node.name.startswith("test_compiled"):
        assert jax.default_backend() == "tpu", \
            "TPU_TASK_TEST_REAL_TPU=1 but no TPU backend initialized"


def _close(actual, desired, rel=0.02):
    actual = np.asarray(actual, dtype=np.float32)
    desired = np.asarray(desired, dtype=np.float32)
    scale = np.abs(desired).max() + 1e-9
    assert np.abs(actual - desired).max() <= rel * scale, \
        f"max err {np.abs(actual - desired).max():.5f} vs scale {scale:.5f}"


@on_tpu
def test_compiled_fused_xent_matches_dense():
    """fused_xent (blockwise online-logsumexp, custom VJP) vs materialized
    logits, loss AND gradients, compiled at an uneven vocab (pad columns)."""
    from tpu_task.ml.models.transformer import fused_xent

    tokens, d, vocab, block = 512, 256, 5000, 2048  # vocab % block != 0
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    features = jax.random.normal(keys[0], (tokens, d), jnp.bfloat16)
    unembed = jax.random.normal(keys[1], (d, vocab), jnp.bfloat16) * 0.02
    targets = jax.random.randint(keys[2], (tokens,), 0, vocab)

    def dense(features, unembed):
        logits = jnp.dot(features, unembed,
                         preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        target_logit = jnp.take_along_axis(
            logits, targets[:, None], axis=1)[:, 0]
        return jnp.mean(lse - target_logit)

    fused = jax.jit(jax.value_and_grad(
        lambda f, u: fused_xent(f, u, targets, block), argnums=(0, 1)))
    ref = jax.jit(jax.value_and_grad(dense, argnums=(0, 1)))
    loss_f, grads_f = fused(features, unembed)
    loss_r, grads_r = ref(features, unembed)
    _close(loss_f, loss_r, rel=0.005)
    for got, want in zip(grads_f, grads_r):
        _close(got, want)


@on_tpu
def test_compiled_moe_topk_dense_matches_cpu_math():
    """MoE top-k dense path compiled on the chip vs the same math re-derived
    in f64-free numpy: routing is discrete, so outputs must agree to bf16
    tolerance, and grads must be finite and nonzero."""
    from tpu_task.ml.models import moe

    cfg = moe.MoEConfig(d_model=128, d_ff=256, n_experts=4, top_k=2)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 128))

    out, aux = jax.jit(lambda p, x: moe.apply_dense(p, cfg, x))(params, x)
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) > 0.0  # load-balance loss is a positive density product

    def loss(p):
        o, a = moe.apply_dense(p, cfg, x)
        return (o.astype(jnp.float32) ** 2).sum() + a

    grads = jax.jit(jax.grad(loss))(params)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert any(n > 0 for n in norms)


@on_tpu
def test_compiled_train_step_loss_decreases():
    """One-chip train step (the MFU headline path: flash attention custom
    VJP + fused xent + adamw, donated buffers) compiled at tiny shapes:
    loss must be finite and decrease over a few steps."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=1024, d_model=128, n_layers=2, n_heads=4, d_head=32,
        d_ff=256, dtype=jnp.bfloat16)
    state = train.init_state(jax.random.PRNGKey(0), cfg)
    step = train.make_train_step(cfg, donate=True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 257), 0,
                                cfg.vocab_size)
    losses = []
    for _ in range(8):
        state, metrics = step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


@on_tpu
def test_compiled_moe_sharded_degenerate_matches_dense():
    """The expert-parallel path (shard_map + all_to_all dispatch/return)
    COMPILED on one chip as a degenerate ep=1 mesh: with capacity high
    enough to drop nothing it must match the dense reference to bf16-ish
    tolerance. Pins the sharded dispatch/combine plumbing on hardware —
    the virtual-mesh CPU tests cover multi-shard numerics."""
    from tpu_task.ml.models import moe
    from tpu_task.ml.parallel import mesh as meshlib

    cfg = moe.MoEConfig(d_model=128, d_ff=256, n_experts=4, top_k=2,
                        capacity_factor=4.0)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 128))
    mesh = meshlib.make_mesh(1, axis_names=("ep",), axis_sizes=(1,))

    dense_out, dense_aux = jax.jit(
        lambda p, x: moe.apply_dense(p, cfg, x))(params, x)
    sharded_out, sharded_aux = jax.jit(
        lambda p, x: moe.apply_sharded(p, cfg, x, mesh))(params, x)
    _close(sharded_out, dense_out)
    _close(sharded_aux, dense_aux)


@on_tpu
def test_compiled_moe_flagship_step_matches_dense_dispatch():
    """The INTEGRATED MoE flagship train step (make_moe_train_step: shard_map
    + all_to_all dispatch inside the real loss) compiled on the chip as a
    degenerate dp=1×ep=1 mesh vs the dense-dispatch step — loss must agree;
    multi-shard numerics are pinned on the virtual CPU mesh."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer
    from tpu_task.ml.parallel import mesh as meshlib

    cfg = transformer.TransformerConfig(
        vocab_size=512, d_model=128, n_layers=2, n_heads=4, d_head=32,
        d_ff=256, dtype=jnp.bfloat16, moe_every=2, n_experts=4,
        moe_capacity_factor=8.0)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 129), 0,
                                cfg.vocab_size)

    ref_state = train.init_state(jax.random.PRNGKey(0), cfg)
    ref_step = train.make_train_step(cfg, donate=False)
    _, ref_metrics = ref_step(ref_state, tokens)

    mesh = meshlib.make_mesh(1, axis_names=("dp", "ep"), axis_sizes=(1, 1))
    state = train.init_state(jax.random.PRNGKey(0), cfg)
    state, _ = train.shard_state(state, cfg, mesh)
    step = train.make_moe_train_step(cfg, mesh, donate=False)(state)
    _, metrics = step(state, tokens)
    _close(float(metrics["loss"]), float(ref_metrics["loss"]), rel=0.01)


@on_tpu
def test_compiled_pp_flagship_step_matches_sequential():
    """The INTEGRATED pipeline flagship train step (1F1B shard_map schedule
    over the real layers, embed-gradient via dx, head loss per microbatch)
    compiled on the chip as a degenerate pp=1 mesh vs the sequential step."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer
    from tpu_task.ml.parallel import mesh as meshlib

    cfg = transformer.TransformerConfig(
        vocab_size=512, d_model=128, n_layers=2, n_heads=4, d_head=32,
        d_ff=256, dtype=jnp.bfloat16)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 129), 0,
                                cfg.vocab_size)

    ref_state = train.init_state(jax.random.PRNGKey(0), cfg)
    ref_step = train.make_train_step(cfg, donate=False)
    _, ref_metrics = ref_step(ref_state, tokens)

    mesh = meshlib.make_mesh(1, axis_names=("pp",), axis_sizes=(1,))
    state = train.init_pp_state(jax.random.PRNGKey(0), cfg, 1)
    state, _ = train.shard_pp_state(state, mesh)
    step = train.make_pp_train_step(cfg, mesh, n_microbatches=2,
                                    donate=False)(state)
    _, metrics = step(state, tokens)
    _close(float(metrics["loss"]), float(ref_metrics["loss"]), rel=0.01)


@on_tpu
def test_compiled_generate_on_chip():
    """KV-cache generation (prefill + scan of cached single-token steps)
    compiled at bf16: runs, stays in-vocab, and greedy is deterministic."""
    from tpu_task.ml.models import decoding, transformer

    cfg = transformer.TransformerConfig(
        vocab_size=1024, d_model=128, n_layers=2, n_heads=4, d_head=32,
        d_ff=256, dtype=jnp.bfloat16)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    jitted = jax.jit(lambda p, t: decoding.generate(p, cfg, t, 32))
    a = np.asarray(jitted(params, prompt))
    b = np.asarray(jitted(params, prompt))
    assert a.shape == (2, 32)
    assert a.max() < cfg.vocab_size and a.min() >= 0
    np.testing.assert_array_equal(a, b)
