"""Input pipeline: deterministic shuffling, prefetch placement."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from tpu_task.ml.data import epoch_batches, prefetch_to_device
from tpu_task.ml.parallel import mesh as meshlib


def test_epoch_batches_cover_dataset_once():
    data = np.arange(10)
    batches = list(epoch_batches(data, None, 2, epochs=1))
    assert len(batches) == 5
    seen = np.sort(np.concatenate(batches))
    np.testing.assert_array_equal(seen, data)


def test_epoch_batches_deterministic_and_reshuffled():
    data = np.arange(64)
    first = [b.tolist() for b in epoch_batches(data, None, 8, seed=1, epochs=1)]
    again = [b.tolist() for b in epoch_batches(data, None, 8, seed=1, epochs=1)]
    assert first == again
    two_epochs = list(epoch_batches(data, None, 8, seed=1, epochs=2))
    assert [b.tolist() for b in two_epochs[:8]] == first
    assert [b.tolist() for b in two_epochs[8:]] != first  # epoch reshuffle


def test_epoch_batches_drop_remainder_and_labels():
    data, labels = np.arange(10), np.arange(10) * 2
    batches = list(epoch_batches(data, labels, 3, epochs=1))
    assert len(batches) == 3  # 10 // 3, remainder dropped
    for x, y in batches:
        np.testing.assert_array_equal(y, x * 2)
    with pytest.raises(ValueError):
        next(epoch_batches(data, None, 11))


def test_prefetch_places_on_sharding():
    mesh = meshlib.make_mesh(8, axis_names=("dp",), axis_sizes=(8,))
    sharding = NamedSharding(mesh, PartitionSpec("dp"))
    data = np.arange(32, dtype=np.float32).reshape(4, 8)
    out = list(prefetch_to_device(iter(data), sharding=sharding, depth=2))
    assert len(out) == 4
    for i, batch in enumerate(out):
        assert batch.sharding == sharding
        np.testing.assert_array_equal(np.asarray(batch), data[i])


def test_prefetch_short_iterator():
    assert list(prefetch_to_device(iter([np.zeros(2)]), depth=4))[0].shape == (2,)
    assert list(prefetch_to_device(iter([]), depth=2)) == []


def test_epoch_batches_host_shards_reassemble_global_batch():
    """Per-host slices concatenate (in process order) to exactly the
    single-host global batch — the zero-communication multi-host contract."""
    from tpu_task.ml.data import epoch_batches

    data = np.arange(64, dtype=np.float32).reshape(32, 2)
    whole = list(epoch_batches(data, None, 8, seed=3, epochs=2,
                               process_index=0, process_count=1))
    shards = [list(epoch_batches(data, None, 8, seed=3, epochs=2,
                                 process_index=i, process_count=4))
              for i in range(4)]
    assert len(whole) == len(shards[0]) == 8  # 4 steps/epoch x 2
    for step, full in enumerate(whole):
        stitched = np.concatenate([shards[i][step] for i in range(4)])
        np.testing.assert_array_equal(stitched, full)
        assert shards[0][step].shape == (2, 2)  # 8 global / 4 hosts


def test_epoch_batches_start_step_resumes_exact_sequence():
    """start_step=N yields exactly the tail the unbroken run would have
    produced — across epoch boundaries (checkpoint-resume contract)."""
    from tpu_task.ml.data import epoch_batches

    data = np.arange(40, dtype=np.int64)
    full = list(epoch_batches(data, None, 10, seed=7, epochs=3,
                              process_index=0, process_count=1))
    for start in (0, 3, 4, 5, 11):
        resumed = list(epoch_batches(data, None, 10, seed=7, epochs=3,
                                     process_index=0, process_count=1,
                                     start_step=start))
        assert len(resumed) == len(full) - start
        for a, b in zip(resumed, full[start:]):
            np.testing.assert_array_equal(a, b)


def test_epoch_batches_rejects_indivisible_global_batch():
    from tpu_task.ml.data import epoch_batches

    with np.testing.assert_raises(ValueError):
        next(epoch_batches(np.zeros((16, 1)), None, 10,
                           process_index=0, process_count=4))
