"""TPU backend: accelerator grammar, fake control-plane state machine,
hermetic full lifecycle over QueuedResources, preemption → re-queue recovery,
multi-host worker fan-out."""

import os
import time

import pytest

from tpu_task.backends.tpu import (
    FakeTpuControlPlane,
    InvalidAcceleratorError,
    QueuedResourceSpec,
    parse_accelerator,
    resolve_zone,
)
from tpu_task.backends.tpu import api as tpu_api
from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    SPOT_ENABLED,
    Environment,
    Size,
    StatusCode,
    Task as TaskSpec,
)
from tpu_task import task as task_factory


# --- accelerator grammar ----------------------------------------------------

@pytest.mark.parametrize("machine,chips,workers", [
    ("v2-8", 4, 1),
    ("v3-32", 16, 4),
    ("v4-8", 4, 1),
    ("v4-32", 16, 4),       # BASELINE config 5: 4 workers
    ("v5p-128", 64, 16),
    ("v5litepod-16", 16, 2),
    ("v6e-8", 8, 1),
])
def test_accelerator_topologies(machine, chips, workers):
    accelerator = parse_accelerator(machine)
    assert accelerator.chips == chips
    assert accelerator.workers == workers


def test_generic_size_aliases():
    assert parse_accelerator("m").type == "v2-8"
    assert parse_accelerator("xl").type == "v4-8"


def test_invalid_accelerators():
    for bad in ("v99-8", "a100", "v4-7", "v4"):
        with pytest.raises(InvalidAcceleratorError):
            parse_accelerator(bad)


def test_zone_resolution():
    assert resolve_zone("us-central2") == "us-central2-b"
    assert resolve_zone("europe-west4-a") == "europe-west4-a"
    with pytest.raises(ValueError):
        resolve_zone("nowhere")


# --- fake control plane state machine ---------------------------------------

@pytest.fixture
def plane(tmp_path):
    return FakeTpuControlPlane(root=str(tmp_path / "tpu"), run_workers=False)


def qr_spec(accelerator="v4-8", node_id="node-1", spot=False):
    return QueuedResourceSpec(
        node_id=node_id, accelerator_type=accelerator,
        runtime_version="tpu-ubuntu2204-base", spot=spot)


def test_qr_progresses_to_active(plane):
    """Each observation is one tick: WAITING at rest, then PROVISIONING,
    then ACTIVE with a READY node."""
    plane.create_queued_resource("qr-1", qr_spec())
    states = [plane.get_queued_resource("qr-1").state for _ in range(3)]
    assert states == [tpu_api.QR_PROVISIONING, tpu_api.QR_ACTIVE, tpu_api.QR_ACTIVE]
    node = plane.get_node("node-1")
    assert node.state == tpu_api.NODE_READY
    assert node.worker_count == 1


def test_qr_create_is_idempotent(plane):
    plane.create_queued_resource("qr-1", qr_spec())
    plane.get_queued_resource("qr-1")
    plane.create_queued_resource("qr-1", qr_spec())  # second create: no reset
    # Progress continues from PROVISIONING; a reset would restart at WAITING.
    assert plane.get_queued_resource("qr-1").state == tpu_api.QR_ACTIVE


def test_stockout_keeps_waiting(tmp_path):
    plane = FakeTpuControlPlane(root=str(tmp_path / "tpu"), run_workers=False,
                                capacity_chips=16)
    plane.create_queued_resource("qr-big", qr_spec("v4-32", "node-big"))
    for _ in range(3):
        plane.get_queued_resource("qr-big")
    assert plane.get_queued_resource("qr-big").state == tpu_api.QR_ACTIVE
    # Second slice exceeds 16-chip capacity → queued indefinitely.
    plane.create_queued_resource("qr-2", qr_spec("v4-32", "node-2"))
    for _ in range(5):
        assert plane.get_queued_resource("qr-2").state == tpu_api.QR_WAITING
    # Capacity frees → granted.
    plane.delete_node("node-big")
    plane.get_queued_resource("qr-2")
    assert plane.get_queued_resource("qr-2").state in (
        tpu_api.QR_PROVISIONING, tpu_api.QR_ACTIVE)


def test_no_overcommit_while_provisioning(tmp_path):
    """Two WAITING requests must not both pass the capacity check before
    either node materializes (PROVISIONING holds capacity)."""
    plane = FakeTpuControlPlane(root=str(tmp_path / "tpu"), run_workers=False,
                                capacity_chips=4)
    plane.create_queued_resource("qr-a", qr_spec("v2-8", "node-a"))
    plane.create_queued_resource("qr-b", qr_spec("v2-8", "node-b"))
    states = set()
    for _ in range(6):
        states = {plane.get_queued_resource("qr-a").state,
                  plane.get_queued_resource("qr-b").state}
    assert tpu_api.QR_ACTIVE in states
    assert tpu_api.QR_WAITING in states  # one of them never got capacity


def test_preempt_kills_running_worker_processes(tmp_path, monkeypatch):
    """Worker PIDs persist to the node record; preemption really kills the
    agent subprocesses (no orphans corrupting the bucket post-preemption)."""
    import json as json_module
    import os as os_module

    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    plane = FakeTpuControlPlane(root=str(tmp_path / "tpu"), run_workers=True)
    bucket = tmp_path / "bucket"
    bucket.mkdir()
    import base64

    spec = qr_spec()
    spec.metadata = {
        "tpu-task-remote": str(bucket),
        "tpu-task-script-b64": base64.b64encode(
            b"#!/bin/bash\nsleep 300\n").decode(),
        "tpu-task-log-period": "0.1",
        "tpu-task-data-period": "0.1",
    }
    plane.create_queued_resource("qr-1", spec)
    while plane.get_queued_resource("qr-1").state != tpu_api.QR_ACTIVE:
        time.sleep(0.05)
    node = json_module.loads(
        (tmp_path / "tpu" / "nodes" / "node-1.json").read_text())
    pids = [w["pid"] for w in node["workers"]]
    assert all(pid > 0 for pid in pids), "worker pids must be persisted"
    plane.preempt_node("node-1")
    deadline = time.time() + 10
    while time.time() < deadline:
        alive = [pid for pid in pids if _pid_alive(pid)]
        if not alive:
            break
        time.sleep(0.1)
    assert not alive, f"agent processes survived preemption: {alive}"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    # Killed-but-unreaped children of this test process show as zombies.
    try:
        with open(f"/proc/{pid}/stat") as handle:
            return handle.read().split(") ")[-1].split()[0] != "Z"
    except OSError:
        return False


def test_preemption_suspends_and_requeue_recovers(plane):
    plane.create_queued_resource("qr-1", qr_spec(spot=True))
    while plane.get_queued_resource("qr-1").state != tpu_api.QR_ACTIVE:
        pass
    plane.preempt_node("node-1")
    assert plane.get_queued_resource("qr-1").state == tpu_api.QR_SUSPENDED
    plane.requeue("qr-1")
    states = [plane.get_queued_resource("qr-1").state for _ in range(3)]
    assert states[-1] == tpu_api.QR_ACTIVE
    codes = [event["code"] for event in plane.get_queued_resource("qr-1").events]
    assert "REQUEUE" in codes


def test_multihost_node_has_worker_endpoints(plane):
    plane.create_queued_resource("qr-mh", qr_spec("v4-32", "node-mh"))
    while plane.get_queued_resource("qr-mh").state != tpu_api.QR_ACTIVE:
        pass
    node = plane.get_node("node-mh")
    assert node.worker_count == 4
    assert len(set(node.endpoints)) == 4


def test_delete_queued_resource_force_deletes_node(plane):
    plane.create_queued_resource("qr-1", qr_spec())
    while plane.get_queued_resource("qr-1").state != tpu_api.QR_ACTIVE:
        pass
    plane.delete_queued_resource("qr-1", force=True)
    with pytest.raises(ResourceNotFoundError):
        plane.get_node("node-1")
    with pytest.raises(ResourceNotFoundError):
        plane.delete_queued_resource("qr-1")


# --- hermetic TPU task lifecycle --------------------------------------------

@pytest.fixture
def tpu_cloud(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    return Cloud(provider=Provider.TPU, region="us-central2")


def poll(task, predicate, timeout=30.0, period=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        task.read()
        if predicate(task):
            return
        time.sleep(period)
    raise AssertionError(f"condition not reached; status={task.status()} "
                         f"logs={task.logs()}")


def test_tpu_full_lifecycle(tpu_cloud, tmp_path):
    workdir = tmp_path / "work"
    workdir.mkdir()
    (workdir / "input.txt").write_text("tpu-payload")
    spec = TaskSpec(
        size=Size(machine="v4-8"),
        environment=Environment(
            script="#!/bin/bash\ncat input.txt\n"
                   "mkdir -p output && echo ok > output/r.txt\n",
            directory=str(workdir), directory_out="output",
        ),
    )
    identifier = Identifier.deterministic("tpu-e2e")
    task = task_factory.new(tpu_cloud, identifier, spec)
    task.delete()
    task.create()
    task.create()  # idempotent double-invoke
    try:
        assert identifier in task_factory.list_tasks(tpu_cloud)
        poll(task, lambda t: t.status().get(StatusCode.SUCCEEDED, 0) >= 1)
        assert "tpu-payload" in "".join(task.logs())
        key_pair = task.get_key_pair()
        assert key_pair is not None and key_pair.public_string().startswith("ssh-rsa")
    finally:
        task.delete()
    assert (workdir / "output" / "r.txt").read_text() == "ok\n"
    task.delete()  # double delete tolerated
    assert identifier not in task_factory.list_tasks(tpu_cloud)


def test_tpu_multihost_workers_all_run(tpu_cloud, tmp_path):
    """A v4-32 slice runs the script on all 4 workers with distinct ranks and
    shared TPU_WORKER_HOSTNAMES (jax.distributed wiring)."""
    spec = TaskSpec(
        size=Size(machine="v4-32"),
        environment=Environment(
            script='#!/bin/bash\necho "rank=$TPU_WORKER_ID hosts=$TPU_WORKER_HOSTNAMES"\n'
                   "sleep 2\n",
        ),
    )
    task = task_factory.new(tpu_cloud, Identifier.deterministic("tpu-multihost"), spec)
    task.create()
    try:
        # While the slice is alive: all 4 worker endpoints exported.
        # Generous timeouts: 4 agent subprocesses + sync loops under full-
        # suite load can take tens of seconds on a busy machine (observed
        # >90 s once with a concurrent 1 GiB data-plane bench running).
        poll(task, lambda t: len(t.get_addresses()) == 4, timeout=90)
        poll(task, lambda t: t.status().get(StatusCode.SUCCEEDED, 0) >= 4,
             timeout=180)
        logs = "".join(task.logs())
        for rank in range(4):
            assert f"rank={rank}" in logs
        assert logs.count("10.130.0.1,10.130.0.2,10.130.0.3,10.130.0.4") >= 4
    finally:
        task.delete()


def test_tpu_preemption_recovery_mttr(tpu_cloud, tmp_path):
    """Spot slice preempted mid-task → reconciler re-queues → respawned slice
    restores the checkpoint from the bucket and succeeds. MTTR measurable
    from the recovery events."""
    script = (
        "#!/bin/bash\n"
        "if test -f checkpoint; then\n"
        "  echo resumed-from-$(cat checkpoint)\n"
        "else\n"
        "  echo cold-start\n"
        "  echo step-40 > checkpoint\n"
        "  sleep 300\n"
        "fi\n"
    )
    spec = TaskSpec(
        size=Size(machine="v4-8"),
        environment=Environment(script=script),
        spot=SPOT_ENABLED,
    )
    task = task_factory.new(tpu_cloud, Identifier.deterministic("tpu-preempt"), spec)
    task.create()
    try:
        poll(task, lambda t: "cold-start" in "".join(t.logs()), timeout=60)
        bucket = task._bucket_dir
        deadline = time.time() + 15
        while time.time() < deadline:
            if os.path.exists(os.path.join(bucket, "data", "checkpoint")):
                break
            time.sleep(0.1)

        preempt_time = time.time()
        task.client.preempt_node(task._qr_name(0))
        poll(task, lambda t: "resumed-from-step-40" in "".join(t.logs()), timeout=30)
        mttr = time.time() - preempt_time
        assert mttr < 30
        codes = [event.code for event in task.events()]
        assert "recover" in codes or "REQUEUE" in codes
    finally:
        task.delete()


def test_recovery_through_fresh_task_with_empty_spec(tpu_cloud):
    """Flagship regression: a bare `tpu-task read` — fresh process, empty
    TaskSpec, spot disabled by default — must still recover a preempted spot
    slice, re-queueing it with the ORIGINAL startup script taken from the
    control plane's own QR record, not a re-render of the empty local spec
    (reference analog: MIG auto-healing needs no client state,
    resource_instance_group_manager.go:103-131)."""
    script = "#!/bin/bash\necho original-workload\nsleep 300\n"
    spec = TaskSpec(size=Size(machine="v4-8"),
                    environment=Environment(script=script),
                    spot=SPOT_ENABLED)
    identifier = Identifier.deterministic("tpu-bare-read")
    task = task_factory.new(tpu_cloud, identifier, spec)
    task.create()
    try:
        poll(task, lambda t: t.client.get_queued_resource(
            t._qr_name(0)).state == tpu_api.QR_ACTIVE, timeout=60)
        original = task.client.get_queued_resource(task._qr_name(0)).spec
        assert original.metadata.get("tpu-task-script-b64")
        task.client.preempt_node(task._qr_name(0))

        fresh = task_factory.new(tpu_cloud, identifier, TaskSpec())
        assert fresh.spec.spot < 0  # the CLI default: spot disabled
        fresh.read()
        assert "recover" in [event.code for event in fresh.events()]
        requeued = fresh.client.get_queued_resource(fresh._qr_name(0))
        assert requeued.spec.startup_script == original.startup_script
        assert requeued.spec.metadata.get("tpu-task-script-b64") == \
            original.metadata.get("tpu-task-script-b64")
        assert requeued.spec.spot  # the re-queued slice stays a spot slice

        # The MTTR record is DURABLE: a second observer that performed no
        # recovery itself sees the recovery event from the bucket mailbox
        # (reports/events-*), the way the reference folds ASG scaling
        # activities into Events (resource_auto_scaling_group.go:158-183).
        observer = task_factory.new(tpu_cloud, identifier, TaskSpec())
        assert observer._recovery_events == []  # nothing in-memory
        recovered = [event for event in observer.events()
                     if event.code == "recover"]
        assert recovered, "recovery event not visible to a fresh observer"
        assert recovered[0].time.tzinfo is not None  # MTTR-computable stamp
    finally:
        task.delete()


def test_tpu_cli_end_to_end(tpu_cloud, tmp_path, monkeypatch):
    """The CLI drives the TPU backend hermetically (cloud=tpu + fake plane)."""
    import subprocess
    import sys

    workdir = tmp_path / "w"
    workdir.mkdir()
    env = dict(os.environ)
    env["TPU_TASK_FAKE_TPU_ROOT"] = os.environ["TPU_TASK_FAKE_TPU_ROOT"]
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    result = subprocess.run(
        [sys.executable, "-m", "tpu_task.cli", "--cloud", "tpu",
         "create", "--name", "cli-tpu", "--machine", "v4-8",
         "--workdir", str(workdir), "--script", "echo via-cli-on-tpu"],
        capture_output=True, text=True, timeout=60, env=env)
    assert result.returncode == 0, result.stderr
    identifier = result.stdout.strip().splitlines()[-1]

    follow = subprocess.run(
        [sys.executable, "-m", "tpu_task.cli", "--cloud", "tpu",
         "read", identifier, "--follow", "--poll-period", "0.2"],
        capture_output=True, text=True, timeout=60, env=env)
    assert follow.returncode == 0, follow.stderr
    assert "via-cli-on-tpu" in follow.stdout

    assert subprocess.run(
        [sys.executable, "-m", "tpu_task.cli", "--cloud", "tpu",
         "delete", identifier],
        capture_output=True, text=True, timeout=60, env=env).returncode == 0


def test_recovery_restores_agent_wheel_url(tpu_cloud, tmp_path, monkeypatch):
    """A bare-read recovery must re-render the bootstrap WITH the staged
    agent-wheel URL recorded in the queued resource's metadata — otherwise
    the respawned worker falls back to a package index that may not have
    the agent at all."""
    spec = TaskSpec(size=Size(machine="v4-8"),
                    environment=Environment(script="#!/bin/bash\nsleep 60\n"),
                    spot=SPOT_ENABLED)
    task = task_factory.new(tpu_cloud, Identifier.deterministic("wheel-rec"), spec)
    task._agent_wheel_url = "https://gcs/b/o/agent.whl?alt=media"
    task.start()
    try:
        qr = task.client.get_queued_resource(task._qr_name(0))
        assert qr.spec.metadata["tpu-task-agent-wheel"] == \
            "https://gcs/b/o/agent.whl?alt=media"

        # Fresh process, empty spec: _recover must carry the URL through.
        bare = task_factory.new(tpu_cloud,
                                Identifier.deterministic("wheel-rec"),
                                TaskSpec())
        info = bare.client.get_queued_resource(task._qr_name(0))
        bare._recover(info)
        assert bare._agent_wheel_url == "https://gcs/b/o/agent.whl?alt=media"
    finally:
        task.stop()
