"""Expert parallelism (MoE all_to_all over ep) and pipeline parallelism
(GPipe over pp) on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_task.ml.models import moe
from tpu_task.ml.parallel import mesh as meshlib
from tpu_task.ml.parallel.pipeline import pipeline_apply


# --- MoE ---------------------------------------------------------------------

def test_moe_dense_forward_shapes():
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    out, aux = moe.apply_dense(params, cfg, x)
    assert out.shape == x.shape
    assert float(aux) > 0


def test_moe_sharded_matches_dense():
    """ep=4 all_to_all dispatch == dense one-hot dispatch (ample capacity)."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=float(4))  # capacity == tokens: no drops
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))

    ref, _ = moe.apply_dense(params, cfg, x)
    out, aux = moe.apply_sharded(params, cfg, x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_capacity_dropped_tokens_pass_through():
    """Tiny capacity with dropped_identity=True: overflow assignments
    contribute a gate-weighted IDENTITY instead of zero — for residual-free
    wirings where zero would erase the token (VERDICT r2 weak #9). The
    default policy stays zero (the external residual is the pass-through)."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=0.5,
                        dropped_identity=True)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))
    out, _ = moe.apply_sharded(params, cfg, x, mesh)
    assert out.shape == x.shape

    # Recompute routing per shard to find the dropped assignments.
    dropped_total = 0
    for shard_index in range(4):
        tokens = np.asarray(x[shard_index]).reshape(8, 16)
        expert_index, gate, _ = moe._route(
            jnp.asarray(tokens), params["router"], cfg)
        expert_index = np.asarray(expert_index)[:, 0]
        gate = np.asarray(gate)[:, 0]
        capacity = max(1, int(cfg.capacity_factor * 8 * cfg.top_k
                              / cfg.n_experts))  # same formula as apply_sharded
        seen: dict = {}
        for token in range(8):
            expert = int(expert_index[token])
            seen[expert] = seen.get(expert, 0) + 1
            if seen[expert] > capacity:  # dropped → identity pass-through
                dropped_total += 1
                np.testing.assert_allclose(
                    np.asarray(out[shard_index]).reshape(8, 16)[token],
                    gate[token] * tokens[token], atol=1e-5)
    assert dropped_total > 0  # the scenario actually exercised drops


def test_moe_top2_sharded_matches_dense():
    """Top-2 routing with ample capacity: expert-parallel equals dense."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=8.0, top_k=2)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 8, 16))
    out, aux = moe.apply_sharded(params, cfg, x, mesh)
    ref, ref_aux = moe.apply_dense(params, cfg, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_top2_gates_renormalized():
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, top_k=2)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(3), (32, 16))
    _, gate, _ = moe._route(x, params["router"], cfg)
    np.testing.assert_allclose(np.asarray(gate.sum(-1)),
                               np.ones(32), atol=1e-6)


def test_moe_requires_divisible_experts():
    cfg = moe.MoEConfig(n_experts=3)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jnp.zeros((4, 2, cfg.d_model))
    with pytest.raises(ValueError, match="divisible"):
        moe.apply_sharded(params, cfg, x, mesh)


# --- pipeline ----------------------------------------------------------------

def _stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def _stacked_params(key, n_stages, d):
    k1, k2 = jax.random.split(key)
    return {
        "w": jax.random.normal(k1, (n_stages, d, d)) * (d ** -0.5),
        "b": jax.random.normal(k2, (n_stages, d)) * 0.1,
    }


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (8, 4)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    d = 16
    params = _stacked_params(jax.random.PRNGKey(0), n_stages, d)
    mesh = meshlib.make_mesh(n_stages, axis_names=("pp",),
                             axis_sizes=(n_stages,))
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro * 2, d))

    # Sequential reference: apply every stage in order.
    ref = x
    for stage in range(n_stages):
        ref = _stage_fn(jax.tree.map(lambda p: p[stage], params), ref)

    out = pipeline_apply(_stage_fn, params, x, mesh, n_microbatches=n_micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_rejects_ragged_microbatches():
    params = _stacked_params(jax.random.PRNGKey(0), 4, 8)
    mesh = meshlib.make_mesh(4, axis_names=("pp",), axis_sizes=(4,))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply(_stage_fn, params, jnp.zeros((7, 8)), mesh,
                       n_microbatches=4)


def test_pipeline_gradients_flow():
    n_stages, d = 4, 8
    params = _stacked_params(jax.random.PRNGKey(0), n_stages, d)
    mesh = meshlib.make_mesh(n_stages, axis_names=("pp",),
                             axis_sizes=(n_stages,))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))

    def loss(params):
        return pipeline_apply(_stage_fn, params, x, mesh,
                              n_microbatches=4).sum()

    grads = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()
        assert float(jnp.abs(leaf).sum()) > 0


# --- MoE flagship integration ------------------------------------------------

def _moe_flagship_cfg():
    from tpu_task.ml.models import transformer

    return transformer.TransformerConfig(
        vocab_size=64, d_model=16, n_layers=2, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32, moe_every=2, n_experts=4,
        # Capacity == local tokens: nothing drops, so expert-parallel
        # dispatch must equal the dense reference exactly.
        moe_capacity_factor=float(4))


def test_moe_config_layers_and_init():
    from tpu_task.ml.models import transformer

    cfg = _moe_flagship_cfg()
    assert [cfg.is_moe_layer(i) for i in range(2)] == [False, True]
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    assert "w_gate" in params["layers"][0] and "router" in params["layers"][1]
    assert params["layers"][1]["w_in"].shape == (4, 16, 32)
    # Dense layers init bit-identically to the all-dense config.
    dense_cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=16, n_layers=2, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32)
    dense_params = transformer.init(jax.random.PRNGKey(0), dense_cfg)
    np.testing.assert_array_equal(np.asarray(params["layers"][0]["wq"]),
                                  np.asarray(dense_params["layers"][0]["wq"]))


def test_moe_flagship_loss_includes_aux():
    from tpu_task.ml.models import transformer

    cfg = _moe_flagship_cfg()
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 64)
    loss = transformer.loss_fn(params, cfg, tokens)
    _, aux = transformer.apply_features_with_aux(params, cfg, tokens[:, :-1])
    assert float(aux) > 0
    no_aux_cfg = type(cfg)(**{**cfg.__dict__, "moe_aux_weight": 0.0})
    loss_no_aux = transformer.loss_fn(params, no_aux_cfg, tokens)
    np.testing.assert_allclose(
        float(loss), float(loss_no_aux) + cfg.moe_aux_weight * float(aux),
        rtol=1e-6)


def test_moe_flagship_train_step_matches_dense_dispatch():
    """The REAL integration pin: make_moe_train_step (ep-sharded all_to_all
    dispatch inside the flagship train step, dp×ep mesh) produces the same
    loss and updated params as the single-device dense-dispatch step."""
    from tpu_task.ml import train
    from tpu_task.ml.parallel import mesh as meshlib

    cfg = _moe_flagship_cfg()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 9), 0, 64)

    ref_state = train.init_state(jax.random.PRNGKey(0), cfg)
    ref_step = train.make_train_step(cfg, donate=False)
    ref_state, ref_metrics = ref_step(ref_state, tokens)

    mesh = meshlib.make_mesh(8, axis_names=("dp", "ep"), axis_sizes=(2, 4))
    state = train.init_state(jax.random.PRNGKey(0), cfg)
    state, _ = train.shard_state(state, cfg, mesh)
    step = train.make_moe_train_step(cfg, mesh, donate=False)(state)
    state, metrics = step(state, tokens)

    np.testing.assert_allclose(float(metrics["loss"]),
                               float(ref_metrics["loss"]), atol=1e-5)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_moe_flagship_router_receives_gradient():
    """The aux loss + LM loss must reach the router through the sharded
    dispatch — a stranded router would silently stop balancing."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = _moe_flagship_cfg()
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 9), 0, 64)
    grads = jax.grad(transformer.loss_fn)(params, cfg, tokens)
    router_grad = grads["layers"][1]["router"]
    assert float(jnp.abs(router_grad).sum()) > 0
    for name in ("w_in", "w_out"):
        assert float(jnp.abs(grads["layers"][1][name]).sum()) > 0


def test_moe_train_step_requires_ep_axis():
    from tpu_task.ml import train
    from tpu_task.ml.parallel import mesh as meshlib

    cfg = _moe_flagship_cfg()
    mesh = meshlib.make_mesh(8)  # dp × fsdp × tp, no ep
    with pytest.raises(ValueError, match="ep"):
        train.make_moe_train_step(cfg, mesh)


# -- 1F1B training schedule ---------------------------------------------------


def _stage_mlp(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_stage_params(key, n_stages, d):
    ks = jax.random.split(key, 2 * n_stages)
    return {
        "w": jnp.stack([jax.random.normal(ks[2 * i], (d, d)) * 0.5
                        for i in range(n_stages)]),
        "b": jnp.stack([jax.random.normal(ks[2 * i + 1], (d,)) * 0.1
                        for i in range(n_stages)]),
    }


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 4), (4, 8)])
def test_1f1b_matches_sequential_autodiff(n_stages, n_micro):
    """1F1B loss and per-stage grads equal plain sequential autodiff."""
    from tpu_task.ml.parallel.pipeline import pipeline_train

    d, batch = 8, 16
    mesh = meshlib.make_mesh(n_stages, axis_names=("pp",),
                             axis_sizes=(n_stages,))
    params = _stacked_stage_params(jax.random.PRNGKey(0), n_stages, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    targets = jax.random.normal(jax.random.PRNGKey(2), (batch, d))

    def loss_fn(out, tgt):
        return jnp.mean((out.astype(jnp.float32) - tgt) ** 2)

    loss, grads = pipeline_train(_stage_mlp, params, x, targets, loss_fn,
                                 mesh, n_microbatches=n_micro)

    # Sequential reference: same microbatching (mean of per-microbatch loss).
    def ref_loss(params):
        total = 0.0
        micro = x.reshape(n_micro, batch // n_micro, d)
        micro_t = targets.reshape(n_micro, batch // n_micro, d)
        for m in range(n_micro):
            h = micro[m]
            for s in range(n_stages):
                h = _stage_mlp(jax.tree.map(lambda p: p[s], params), h)
            total = total + loss_fn(h, micro_t[m])
        return total / n_micro

    ref, ref_grads = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref), atol=1e-5)
    for name in ("w", "b"):
        np.testing.assert_allclose(np.asarray(grads[name]),
                                   np.asarray(ref_grads[name]), atol=1e-4)


def test_1f1b_rejects_ragged_microbatches():
    from tpu_task.ml.parallel.pipeline import pipeline_train

    mesh = meshlib.make_mesh(2, axis_names=("pp",), axis_sizes=(2,))
    params = _stacked_stage_params(jax.random.PRNGKey(0), 2, 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (10, 4))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_train(_stage_mlp, params, x, x,
                       lambda o, t: jnp.mean((o - t) ** 2), mesh, 3)


def test_pp_flagship_train_step_matches_sequential():
    """The REAL integration pin: make_pp_train_step (1F1B over the actual
    transformer layers, embed gradient via the pipeline dx, head = final
    norm + unembed + fused xent) equals the plain single-device
    make_train_step — same loss, same updated params after one step."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    n_stages, n_micro = 4, 4
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=16, n_layers=4, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 9), 0, 64)

    ref_state = train.init_state(jax.random.PRNGKey(0), cfg)
    ref_step = train.make_train_step(cfg, donate=False)
    ref_state, ref_metrics = ref_step(ref_state, tokens)

    mesh = meshlib.make_mesh(n_stages, axis_names=("pp",),
                             axis_sizes=(n_stages,))
    state = train.init_pp_state(jax.random.PRNGKey(0), cfg, n_stages)
    state, _ = train.shard_pp_state(state, mesh)
    step = train.make_pp_train_step(cfg, mesh, n_micro, donate=False)(state)
    state, metrics = step(state, tokens)

    np.testing.assert_allclose(float(metrics["loss"]),
                               float(ref_metrics["loss"]), atol=1e-5)
    np.testing.assert_allclose(float(metrics["grad_norm"]),
                               float(ref_metrics["grad_norm"]), atol=1e-4)
    unstacked = train.pp_unstack_params(jax.device_get(state.params))
    for a, b in zip(jax.tree.leaves(unstacked),
                    jax.tree.leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_pp_stack_unstack_roundtrip():
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=32, d_model=8, n_layers=4, n_heads=2, d_head=4,
        d_ff=16, dtype=jnp.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    back = train.pp_unstack_params(train.pp_stack_params(params, 2))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pp_train_step_rejects_bad_split():
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=32, d_model=8, n_layers=3, n_heads=2, d_head=4,
        d_ff=16, dtype=jnp.float32)
    mesh = meshlib.make_mesh(4, axis_names=("pp",), axis_sizes=(4,))
    with pytest.raises(ValueError, match="divisible"):
        train.make_pp_train_step(cfg, mesh, 4)


def test_moe_default_drop_policy_is_zero():
    """Default (external-residual wiring): dropped slots contribute exact
    zeros — switch semantics, no double-count under x + moe(x)."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=0.5)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))
    out, _ = moe.apply_sharded(params, cfg, x, mesh)
    # With capacity 1 per expert per shard, some tokens must be dropped and
    # come back as exact zeros.
    per_token = np.abs(np.asarray(out)).sum(-1)
    assert (per_token == 0).any()


def test_1f1b_trains_transformer_stages():
    """The flagship transformer's blocks compose with the 1F1B schedule:
    stage = a slice of layers, loss at the last stage — grads match the
    sequential model exactly."""
    from tpu_task.ml.models import transformer
    from tpu_task.ml.parallel.pipeline import pipeline_train

    n_stages, layers_per_stage = 4, 1
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=16, n_layers=n_stages * layers_per_stage,
        n_heads=2, d_head=8, d_ff=32, dtype=jnp.float32)
    full = transformer.init(jax.random.PRNGKey(0), cfg)
    # Stage-stack the per-layer params: leading axis = stage.
    stage_params = jax.tree.map(
        lambda *leaves: jnp.stack(leaves), *full["layers"])

    mesh = meshlib.make_mesh(n_stages, axis_names=("pp",),
                             axis_sizes=(n_stages,))
    batch, seq = 8, 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, 64)
    x = transformer.embed_lookup(full["embed"], tokens)
    targets = jax.random.normal(jax.random.PRNGKey(2),
                                (batch, seq, cfg.d_model))

    from tpu_task.ml.ops.attention import mha_reference

    def stage_fn(layer, h):
        out, _aux = transformer._block(
            h, layer, cfg, lambda q, k, v: mha_reference(q, k, v, True))
        return out

    def loss_fn(out, tgt):
        return jnp.mean((out.astype(jnp.float32) - tgt) ** 2)

    loss, grads = pipeline_train(stage_fn, stage_params, x, targets, loss_fn,
                                 mesh, n_microbatches=4)

    def ref_loss(stage_params):
        total = 0.0
        micro = x.reshape(4, batch // 4, seq, cfg.d_model)
        micro_t = targets.reshape(4, batch // 4, seq, cfg.d_model)
        for m in range(4):
            h = micro[m]
            for s in range(n_stages):
                h = stage_fn(jax.tree.map(lambda p: p[s], stage_params), h)
            total = total + loss_fn(h, micro_t[m])
        return total / 4

    ref, ref_grads = jax.value_and_grad(ref_loss)(stage_params)
    np.testing.assert_allclose(float(loss), float(ref), atol=1e-5)
    for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_pp_flagship_composes_with_dp():
    """dp×pp mesh (2×4): each dp group pipelines its own batch slice; the
    combined step still equals the single-device sequential step exactly —
    loss, grad_norm, and updated params."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=16, n_layers=4, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 9), 0, 64)

    ref_state = train.init_state(jax.random.PRNGKey(0), cfg)
    ref_state, ref_metrics = train.make_train_step(
        cfg, donate=False)(ref_state, tokens)

    mesh = meshlib.make_mesh(8, axis_names=("dp", "pp"), axis_sizes=(2, 4))
    state = train.init_pp_state(jax.random.PRNGKey(0), cfg, 4)
    state, _ = train.shard_pp_state(state, mesh)
    step = train.make_pp_train_step(cfg, mesh, n_microbatches=2,
                                    donate=False)(state)
    state, metrics = step(state, tokens)

    np.testing.assert_allclose(float(metrics["loss"]),
                               float(ref_metrics["loss"]), atol=1e-5)
    np.testing.assert_allclose(float(metrics["grad_norm"]),
                               float(ref_metrics["grad_norm"]), atol=1e-4)
    unstacked = train.pp_unstack_params(jax.device_get(state.params))
    for a, b in zip(jax.tree.leaves(unstacked),
                    jax.tree.leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
