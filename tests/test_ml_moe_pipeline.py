"""Expert parallelism (MoE all_to_all over ep) and pipeline parallelism
(GPipe over pp) on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_task.ml.models import moe
from tpu_task.ml.parallel import mesh as meshlib
from tpu_task.ml.parallel.pipeline import pipeline_apply


# --- MoE ---------------------------------------------------------------------

def test_moe_dense_forward_shapes():
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    out, aux = moe.apply_dense(params, cfg, x)
    assert out.shape == x.shape
    assert float(aux) > 0


def test_moe_sharded_matches_dense():
    """ep=4 all_to_all dispatch == dense one-hot dispatch (ample capacity)."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                        capacity_factor=float(4))  # capacity == tokens: no drops
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))

    ref, _ = moe.apply_dense(params, cfg, x)
    out, aux = moe.apply_sharded(params, cfg, x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_capacity_drops_tokens():
    """Tiny capacity: overflow tokens come back as exact zeros (switch
    semantics) and the kept count respects the capacity bound."""
    cfg = moe.MoEConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=0.5)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))
    out, _ = moe.apply_sharded(params, cfg, x, mesh)
    assert out.shape == x.shape
    # Each shard holds 8 tokens; capacity = 0.5 * 8 / 4 = 1 per expert per
    # shard → at most n_experts kept tokens per shard, the rest exact zeros.
    per_shard = np.asarray(out).reshape(4, 8, 16)
    for shard in per_shard:
        nonzero = (np.abs(shard).sum(-1) > 0).sum()
        assert nonzero <= cfg.n_experts, nonzero
    assert (np.abs(per_shard).sum(-1) == 0).any()  # some tokens dropped


def test_moe_requires_divisible_experts():
    cfg = moe.MoEConfig(n_experts=3)
    params = moe.init(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.make_mesh(4, axis_names=("ep",), axis_sizes=(4,))
    x = jnp.zeros((4, 2, cfg.d_model))
    with pytest.raises(ValueError, match="divisible"):
        moe.apply_sharded(params, cfg, x, mesh)


# --- pipeline ----------------------------------------------------------------

def _stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def _stacked_params(key, n_stages, d):
    k1, k2 = jax.random.split(key)
    return {
        "w": jax.random.normal(k1, (n_stages, d, d)) * (d ** -0.5),
        "b": jax.random.normal(k2, (n_stages, d)) * 0.1,
    }


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (8, 4)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    d = 16
    params = _stacked_params(jax.random.PRNGKey(0), n_stages, d)
    mesh = meshlib.make_mesh(n_stages, axis_names=("pp",),
                             axis_sizes=(n_stages,))
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro * 2, d))

    # Sequential reference: apply every stage in order.
    ref = x
    for stage in range(n_stages):
        ref = _stage_fn(jax.tree.map(lambda p: p[stage], params), ref)

    out = pipeline_apply(_stage_fn, params, x, mesh, n_microbatches=n_micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_rejects_ragged_microbatches():
    params = _stacked_params(jax.random.PRNGKey(0), 4, 8)
    mesh = meshlib.make_mesh(4, axis_names=("pp",), axis_sizes=(4,))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply(_stage_fn, params, jnp.zeros((7, 8)), mesh,
                       n_microbatches=4)


def test_pipeline_gradients_flow():
    n_stages, d = 4, 8
    params = _stacked_params(jax.random.PRNGKey(0), n_stages, d)
    mesh = meshlib.make_mesh(n_stages, axis_names=("pp",),
                             axis_sizes=(n_stages,))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))

    def loss(params):
        return pipeline_apply(_stage_fn, params, x, mesh,
                              n_microbatches=4).sum()

    grads = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()
        assert float(jnp.abs(leaf).sum()) > 0
