"""K-token fused decode micro-steps (dispatch amortization, PR 13).

The contract (docs/parity.md "Dispatch amortization"): ``micro_k`` is a
pure SCHEDULING knob — it changes how many decode iterations one
dispatch runs, never a token. Greedy streams at any K are bit-identical
to K=1 and sampled streams key-identical (the per-token
``fold_in(request_key, token_index)`` keys fold in-program from the
iteration's running count, the same stream K=1 draws), across every
production mode stacked since PR 5: chunked prefill, prefix-cache hits,
speculative decoding (spec rounds stay the multi-token path — one path
per slot per step), recompute preemption under pool pressure, and
mid-stream export/resume landing on exact token boundaries mid-block.

Two tier-1 ``perf`` smokes pin the cheap core (greedy identity + the
dispatch-amortization accounting); the wider matrix is ``slow``.
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpu_task.ml.models import transformer
from tpu_task.ml.serving import ServingConfig, ServingEngine

TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32, n_kv_heads=2)

BASE = ServingConfig(slots=4, block_size=4, n_blocks=64, max_len=48,
                     prefill_buckets=(8, 16), chunk_tokens=4)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _workload(seed=0, n=8):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, TINY.vocab_size,
                            size=int(rng.integers(3, 12))) for _ in range(n)]
    max_new = [int(rng.integers(3, 14)) for _ in range(n)]
    return prompts, max_new


def _drain(params, scfg, temps=None, seed=0, n=8, **engine_kw):
    engine = ServingEngine(params, TINY, scfg, **engine_kw)
    prompts, max_new = _workload(seed, n)
    for i, prompt in enumerate(prompts):
        t = 0.0 if temps is None else temps[i]
        engine.submit(prompt, max_new[i], eos_token=7, temperature=t,
                      top_p=0.9 if t > 0 else None)
    return engine.drain(), engine


def test_micro_k_validation():
    with pytest.raises(ValueError, match="micro_k"):
        ServingConfig(micro_k=0)
    with pytest.raises(ValueError, match="micro_k"):
        ServingConfig(micro_k=512, max_len=256)


@pytest.mark.perf
def test_micro_k_greedy_streams_bit_identical_to_k1(params):
    """The tier-1 pin of the tentpole: K=4 greedy streams — through
    chunked prefill, the prefix cache, and mid-block eos/length
    retirement (eos_token set, mixed max_new) — are bit-identical to the
    per-token K=1 engine's, and the K-wide program actually amortizes
    (fewer decode dispatches than tokens decoded)."""
    ref, _ = _drain(params, BASE)
    got, engine = _drain(params, dataclasses.replace(BASE, micro_k=4))
    assert got == ref
    assert engine.micro_steps > 0
    decoded = sum(len(t) for t in got.values())
    # Each micro dispatch covers up to 4 tokens per active slot: far
    # fewer fused-decode dispatches than decoded tokens.
    assert engine.micro_steps < decoded / 2
    assert engine.stats()["micro_k"] == 4


@pytest.mark.perf
def test_micro_k_dispatch_accounting_stays_honest(params):
    """GoodputMeter at K>1: one dispatch per micro-step but K tokens of
    work — dispatches_per_token must DROP vs K=1 on the same workload
    (the per-call accounting would misreport K tokens as one)."""
    from tpu_task.obs import Obs

    def gp(scfg):
        out, engine = _drain(params, scfg,
                             obs=Obs.create(f"micro-{scfg.micro_k}"))
        return out, engine.stats()["goodput"]

    out1, gp1 = gp(BASE)
    out4, gp4 = gp(dataclasses.replace(BASE, micro_k=4))
    assert out1 == out4
    assert gp4["dispatches_per_token"] < gp1["dispatches_per_token"]
    # Work accounting charges per valid token, so the FLOP model (and
    # with it MFU's numerator) is schedule-invariant.
    assert gp4["model_flops"] == pytest.approx(gp1["model_flops"])
    assert gp4["tokens"]["emitted"] == gp1["tokens"]["emitted"]


@pytest.mark.slow
@pytest.mark.parametrize("micro_k", [2, 4])
def test_micro_k_matrix_greedy_identity(params, micro_k):
    """K ∈ {2, 4} greedy bit-identity across the stacked production
    modes: prefix-cache hits (shared prefixes), pool-pressure recompute
    preemption, and bucketed prefill."""
    # Shared prefixes → prefix-cache hits on re-admission.
    rng = np.random.default_rng(3)
    shared = rng.integers(0, TINY.vocab_size, size=8)

    def run(scfg):
        engine = ServingEngine(params, TINY, scfg)
        for i in range(6):
            prompt = np.concatenate(
                [shared, rng.integers(0, TINY.vocab_size, size=1 + i % 3)])
            engine.submit(prompt, 8, eos_token=7)
        return engine.drain(), engine

    rng = np.random.default_rng(3)
    ref, _ = run(BASE)
    rng = np.random.default_rng(3)
    got, engine = run(dataclasses.replace(BASE, micro_k=micro_k))
    assert got == ref
    assert engine.prefix_hit_blocks > 0

    # Pool pressure: tiny pool forces recompute preemption mid-decode.
    tight = dataclasses.replace(BASE, n_blocks=14)
    ref_t, _ = _drain(params, tight)
    got_t, engine_t = _drain(
        params, dataclasses.replace(tight, micro_k=micro_k))
    assert got_t == ref_t
    # And the unpressured engine agrees too (schedule independence).
    assert got_t == _drain(params, BASE)[0]

    # Bucketed prefill path (no chunk program in the loop).
    bucketed = dataclasses.replace(
        BASE, prefill="bucketed", prefix_cache=False)
    assert _drain(params, dataclasses.replace(
        bucketed, micro_k=micro_k))[0] == _drain(params, bucketed)[0]


@pytest.mark.slow
@pytest.mark.parametrize("micro_k", [2, 4])
def test_micro_k_sampled_streams_key_identical(params, micro_k):
    """Sampled streams at K>1 equal K=1's: the micro program folds each
    iteration's key in-program from the running n_generated — the same
    fold_in(request_key, token_index) stream, schedule-independent."""
    temps = [0.8, 0.7, 0.0, 0.9, 0.0, 0.8, 1.1, 0.0]
    ref, _ = _drain(params, BASE, temps=temps)
    got, _ = _drain(params, dataclasses.replace(BASE, micro_k=micro_k),
                    temps=temps)
    assert got == ref


@pytest.mark.slow
def test_micro_k_composes_with_spec_decode(params):
    """One path per slot per step: with speculative decoding on, spec
    rounds ARE the multi-token path and micro_k must not perturb the
    (already pinned bit-exact) spec streams."""
    spec = dataclasses.replace(BASE, spec_k=2)
    ref, _ = _drain(params, spec, draft_params=params, draft_cfg=TINY,
                    n=5)
    got, engine = _drain(params, dataclasses.replace(spec, micro_k=4),
                         draft_params=params, draft_cfg=TINY, n=5)
    assert got == ref
    assert engine.spec_rounds > 0
    assert engine.micro_steps == 0     # spec rounds took the decode path


@pytest.mark.slow
def test_micro_k_export_resume_lands_on_token_boundaries(params):
    """Mid-stream export from a K=4 engine (positions mid-block) resumes
    token-identically in a fresh engine — at K=4 or K=1 — because
    micro-steps commit tokens only at their host sweep, so exports
    always see exact token boundaries."""
    ref, _ = _drain(params, BASE)
    prompts, max_new = _workload()
    for resume_k in (1, 4):
        engine = ServingEngine(
            params, TINY, dataclasses.replace(BASE, micro_k=4))
        for i, prompt in enumerate(prompts):
            engine.submit(prompt, max_new[i], eos_token=7)
        for _ in range(3):
            engine.step()
        records = engine.export_inflight()
        assert records, "nothing in flight after 3 steps"
        done = {rid: list(r.tokens) for rid, r in engine._requests.items()
                if r.status == "done"}
        sibling = ServingEngine(
            params, TINY, dataclasses.replace(BASE, micro_k=resume_k))
        mapping = sibling.resume_inflight(records)
        out = sibling.drain()
        for old, new in mapping.items():
            assert out[new] == ref[old], \
                f"resumed stream {old} diverged at resume_k={resume_k}"
        for rid, toks in done.items():
            assert toks == ref[rid]


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
def test_micro_k_quantized_streams_match_k1(params, kv_dtype):
    """Quantized pools under micro-steps: K=4 streams identical to the
    SAME dtype's K=1 streams (iteration j's write layout is exactly the
    K=1 step's at position + j; a mid-span retiree's garbage rows touch
    only its own never-again-read partial block)."""
    from tpu_task.ml.serving.cache import fp8_supported

    if kv_dtype == "fp8" and not fp8_supported():
        pytest.skip("no fp8 support in this jax build")
    quant = dataclasses.replace(BASE, kv_dtype=kv_dtype)
    ref, _ = _drain(params, quant)
    got, engine4 = _drain(params, dataclasses.replace(quant, micro_k=4))
    assert got == ref
    assert engine4.quantized_block_writes > 0
    assert engine4.stats()["kv_quant"]["kv_dtype"] == kv_dtype


@pytest.mark.slow
def test_micro_k_tp8_matches_single_chip(params):
    """The PR 6 contract holds under micro-steps: a tp=8 K=4 engine's
    greedy streams are bit-identical to the single-chip K=4 (and so K=1)
    engine's."""
    from jax.sharding import Mesh

    if jax.device_count() < 8:
        pytest.skip("needs 8 devices (XLA_FLAGS host platform)")
    cfg8 = dataclasses.replace(TINY, n_heads=8, n_kv_heads=8)
    params8 = transformer.init(jax.random.PRNGKey(0), cfg8)
    scfg = dataclasses.replace(BASE, micro_k=4)

    def run(mesh=None):
        engine = ServingEngine(params8, cfg8, scfg, mesh=mesh)
        prompts, max_new = _workload(n=4)
        for i, prompt in enumerate(prompts):
            engine.submit(prompt, max_new[i])
        return engine.drain()

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("tp",))
    assert run(mesh) == run()
