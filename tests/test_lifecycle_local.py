"""Hermetic full-lifecycle tests driving the L4 Task interface — the shape of
the reference's smoke test (task_smoke_test.go:162-243) with its deliberate
double-invoke idempotency checks, but runnable with zero cloud credentials
against the local fake control plane. Also the preemption-recovery test the
reference cannot express hermetically (SURVEY.md §4)."""

import time
import uuid

import pytest

from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import Environment, StatusCode, Task as TaskSpec, Variables
from tpu_task import task as task_factory


@pytest.fixture
def cloud(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_TASK_LOCAL_ROOT", str(tmp_path / "control-plane"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    return Cloud(provider=Provider.LOCAL)


def poll(task, predicate, timeout=60.0, period=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        task.read()
        if predicate(task):
            return
        time.sleep(period)
    raise AssertionError(
        f"condition not reached; status={task.status()} logs={task.logs()}")


def succeeded(task):
    return task.status().get(StatusCode.SUCCEEDED, 0) >= 1


def failed(task):
    return task.status().get(StatusCode.FAILED, 0) >= 1


def test_full_lifecycle_with_idempotency(cloud, tmp_path):
    """delete → create → create → logs sentinel → status → delete → delete."""
    sentinel = str(uuid.uuid4())
    workdir = tmp_path / "work"
    (workdir / "cache").mkdir(parents=True)
    (workdir / "cache" / "junk.bin").write_text("excluded")
    (workdir / "input.txt").write_text("payload-42")

    spec = TaskSpec()
    spec.environment = Environment(
        script=f"#!/bin/bash\ncat input.txt\necho {sentinel} $SENTINEL_VAR\n"
               "mkdir -p output && echo done > output/result.txt\n",
        variables=Variables({"SENTINEL_VAR": sentinel[:8]}),
        directory=str(workdir),
        directory_out="output",
        exclude_list=["cache/**"],
    )
    identifier = Identifier.deterministic("lifecycle-test")
    task = task_factory.new(cloud, identifier, spec)

    task.delete()          # delete before create: must tolerate NotFound
    task.create()
    task.create()          # double-invoke: idempotent

    assert identifier in task_factory.list_tasks(cloud)

    poll(task, succeeded)
    logs = "".join(task.logs())
    assert sentinel in logs                 # workdir round-trip + script ran
    assert sentinel[:8] in logs             # env-var injection
    assert "payload-42" in logs             # input file present

    task.delete()
    # Pull-on-delete: output/ downloaded, cache/ still excluded from upload.
    assert (workdir / "output" / "result.txt").read_text() == "done\n"
    task.delete()          # double delete: tolerated
    assert identifier not in task_factory.list_tasks(cloud)


def test_failing_task_reports_failed(cloud):
    spec = TaskSpec()
    spec.environment = Environment(script="#!/bin/bash\nexit 7\n")
    task = task_factory.new(cloud, Identifier.deterministic("fail-test"), spec)
    task.create()
    try:
        poll(task, failed)
        status = task.status()
        assert status.get(StatusCode.FAILED, 0) == 1
        assert status.get(StatusCode.SUCCEEDED, 0) == 0
    finally:
        task.delete()


def test_stop_scales_to_zero(cloud):
    spec = TaskSpec()
    spec.environment = Environment(script="#!/bin/bash\nsleep 300\n")
    task = task_factory.new(cloud, Identifier.deterministic("stop-test"), spec)
    task.create()
    try:
        poll(task, lambda t: t.status().get(StatusCode.ACTIVE, 0) == 1, timeout=45)
        task.stop()
        poll(task, lambda t: t.status().get(StatusCode.ACTIVE, 0) == 0, timeout=45)
        assert task.group.desired() == 0
    finally:
        task.delete()


def test_self_destruct_on_completion(cloud):
    """Worker 0 leaves the shutdown marker; the control plane scales to 0 —
    the `leo stop` self-destruct cycle (machine-script.sh.tpl:10-14)."""
    spec = TaskSpec()
    spec.environment = Environment(script="#!/bin/bash\necho quick\n")
    task = task_factory.new(cloud, Identifier.deterministic("selfdestruct"), spec)
    task.create()
    try:
        poll(task, lambda t: succeeded(t) and t.group.desired() == 0)
        events = [event.code for event in task.events()]
        assert "self-destruct" in events
    finally:
        task.delete()


def test_preemption_recovery_resumes_from_checkpoint(cloud):
    """Kill a worker mid-task; the reconciler respawns it and the respawned
    machine restores the bucket checkpoint — ASG spot-recovery semantics
    (resource_auto_scaling_group.go:64-90) made hermetic and observable."""
    script = (
        "#!/bin/bash\n"
        "if test -f checkpoint; then\n"
        "  echo resumed-from-$(cat checkpoint)\n"
        "else\n"
        "  echo cold-start\n"
        "  echo epoch-3 > checkpoint\n"
        "  sync\n"
        "  sleep 300\n"       # preempted during this sleep
        "fi\n"
    )
    spec = TaskSpec()
    spec.environment = Environment(script=script)
    task = task_factory.new(cloud, Identifier.deterministic("preempt-test"), spec)
    task.create()
    try:
        # Wait until the checkpoint reaches the bucket.
        poll(task, lambda t: "cold-start" in "".join(t.logs()), timeout=60)
        deadline = time.time() + 15
        while time.time() < deadline:
            import os
            if os.path.exists(os.path.join(task.group.bucket, "data", "checkpoint")):
                break
            time.sleep(0.1)

        task.preempt(0)
        poll(task, succeeded, timeout=30)
        logs = "".join(task.logs())
        assert "resumed-from-epoch-3" in logs
        preempt_events = [e.code for e in task.events()]
        assert "preempt" in preempt_events
        assert preempt_events.count("launch") >= 2    # original + respawn
    finally:
        task.delete()


def test_parallelism_runs_n_workers(cloud):
    spec = TaskSpec()
    spec.parallelism = 3
    spec.environment = Environment(script="#!/bin/bash\necho worker-$TPU_WORKER_ID\n")
    task = task_factory.new(cloud, Identifier.deterministic("parallel-test"), spec)
    task.create()
    try:
        # Generous timeout: 3 agent subprocesses + sync loops under full-
        # suite load can take tens of seconds on a busy machine.
        poll(task, lambda t: t.status().get(StatusCode.SUCCEEDED, 0)
             + t.status().get(StatusCode.FAILED, 0) >= 3, timeout=180)
        logs = "".join(task.logs())
        for rank in range(3):
            assert f"worker-{rank}" in logs
        assert task.status().get(StatusCode.SUCCEEDED, 0) == 3
    finally:
        task.delete()
