"""Fault-injection tests for the REST resilience layer.

A scripted fake transport plays 500s, 429s (with Retry-After), connection
drops, and expired tokens against the shared http layer, the GCS backend,
and the Cloud TPU client — the failure modes a >1 h real-cloud lifecycle
actually hits. Role in the reference: the cloud SDKs' built-in retry/refresh
(SURVEY.md §2.2-2.3); here we own it, so we test it.
"""

import io
import json
import urllib.error

import pytest

from tpu_task.storage.http_util import OAuthToken, authorized_send, send


class FakeResponse:
    def __init__(self, body=b"", headers=None):
        self._body = body
        self.headers = headers or {}

    def read(self):
        return self._body

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FakeTransport:
    """Plays a script of responses; records every request it sees.

    Script entries: ("ok", body[, headers]) | ("http", code[, headers[,
    body]]) | ("conn",).
    """

    def __init__(self, script):
        self.script = list(script)
        self.requests = []

    def __call__(self, request, timeout=None):
        self.requests.append(request)
        if not self.script:
            raise AssertionError("transport script exhausted")
        entry = self.script.pop(0)
        kind = entry[0]
        if kind == "ok":
            body = entry[1] if len(entry) > 1 else b""
            headers = entry[2] if len(entry) > 2 else {}
            return FakeResponse(body, headers)
        if kind == "http":
            code = entry[1]
            headers = entry[2] if len(entry) > 2 else {}
            error_body = entry[3] if len(entry) > 3 else b""
            import email.message

            message = email.message.Message()
            for key, value in headers.items():
                message[key] = value
            raise urllib.error.HTTPError(
                request.full_url, code, "err", message, io.BytesIO(error_body))
        if kind == "conn":
            raise urllib.error.URLError("connection reset")
        raise AssertionError(f"unknown script entry {entry!r}")


class FakeSleep:
    def __init__(self):
        self.calls = []

    def __call__(self, seconds):
        self.calls.append(seconds)


class TopRng:
    """Jitter stub that always draws the ladder's upper bound, so tests can
    assert the exact exponential envelope."""

    def uniform(self, _low, high):
        return high


def test_send_retries_5xx_then_succeeds():
    transport = FakeTransport([("http", 500), ("http", 503), ("ok", b"done")])
    sleep = FakeSleep()
    body = send("GET", "https://x/y", urlopen=transport, sleep=sleep,
                rng=TopRng())
    assert body == b"done"
    assert len(transport.requests) == 3
    assert sleep.calls == [0.5, 1.0]  # exponential backoff envelope


def test_send_backoff_is_jittered_and_seed_deterministic():
    """Full jitter: waits are uniform in (0, ladder], and an injected seeded
    rng replays the identical sequence — multi-worker retries spread out
    instead of synchronizing into a thundering herd."""
    import random

    waits = {}
    for run in range(2):
        transport = FakeTransport([("http", 500)] * 3 + [("ok", b"ok")])
        sleep = FakeSleep()
        send("GET", "https://x/y", urlopen=transport, sleep=sleep,
             rng=random.Random(7))
        waits[run] = list(sleep.calls)
    assert waits[0] == waits[1]  # replayable from one seed
    for wait, ladder in zip(waits[0], (0.5, 1.0, 2.0)):
        assert 0 <= wait <= ladder
    # Astronomically unlikely to sit exactly on the envelope at every rung.
    assert waits[0] != [0.5, 1.0, 2.0]


def test_send_honors_retry_after():
    transport = FakeTransport([
        ("http", 429, {"Retry-After": "3"}), ("ok", b"ok")])
    sleep = FakeSleep()
    send("GET", "https://x/y", urlopen=transport, sleep=sleep)
    assert sleep.calls == [3.0]


def test_send_retries_connection_errors():
    transport = FakeTransport([("conn",), ("conn",), ("ok", b"ok")])
    assert send("GET", "https://x/y", urlopen=transport,
                sleep=FakeSleep()) == b"ok"


def test_send_gives_up_after_max_retries():
    transport = FakeTransport([("http", 500)] * 6)
    with pytest.raises(urllib.error.HTTPError):
        send("GET", "https://x/y", urlopen=transport, sleep=FakeSleep())
    assert len(transport.requests) == 6  # 1 + 5 retries


def test_send_does_not_retry_client_errors():
    transport = FakeTransport([("http", 403)])
    with pytest.raises(urllib.error.HTTPError):
        send("GET", "https://x/y", urlopen=transport, sleep=FakeSleep())
    assert len(transport.requests) == 1


def test_oauth_token_caches_and_refreshes_on_expiry():
    clock = [1000.0]
    fetches = []

    def fetch():
        fetches.append(clock[0])
        return f"tok-{len(fetches)}", 3600.0

    token = OAuthToken(fetch, early=60.0, now=lambda: clock[0])
    assert token.get() == "tok-1"
    assert token.get() == "tok-1"          # cached
    clock[0] += 3550.0                     # inside the 60 s early-refresh window
    assert token.get() == "tok-2"
    assert len(fetches) == 2


def test_authorized_send_refreshes_once_on_401():
    fetches = []

    def fetch():
        fetches.append(1)
        return f"tok-{len(fetches)}", 3600.0

    token = OAuthToken(fetch)
    transport = FakeTransport([("http", 401), ("ok", b"ok")])
    body = authorized_send(token, "GET", "https://x/y", urlopen=transport,
                           sleep=FakeSleep())
    assert body == b"ok"
    assert len(fetches) == 2  # initial + forced refresh
    auths = [r.get_header("Authorization") for r in transport.requests]
    assert auths == ["Bearer tok-1", "Bearer tok-2"]


# -- GCS backend through the fake transport -----------------------------------


def _gcs(transport):
    from tpu_task.storage.backends import GCSBackend

    backend = GCSBackend("bkt", "pfx")
    backend._token._fetch = lambda: ("tok", 3600.0)
    backend._urlopen = transport
    backend._sleep = FakeSleep()
    return backend


def test_gcs_read_retries_then_succeeds():
    transport = FakeTransport([("http", 502), ("ok", b"payload")])
    assert _gcs(transport).read("a/b.txt") == b"payload"


def test_gcs_read_404_maps_to_not_found():
    from tpu_task.common.errors import ResourceNotFoundError

    transport = FakeTransport([("http", 404)])
    with pytest.raises(ResourceNotFoundError):
        _gcs(transport).read("missing")


def test_gcs_small_write_single_request():
    transport = FakeTransport([("ok", b"{}")])
    _gcs(transport).write("small.bin", b"x" * 128)
    assert len(transport.requests) == 1
    assert b"uploadType=media" in transport.requests[0].full_url.encode()


def test_gcs_large_write_resumable_chunks():
    from tpu_task.storage.backends import GCSBackend

    size = GCSBackend.RESUMABLE_THRESHOLD + GCSBackend.UPLOAD_CHUNK // 2
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-123"}),  # initiate
        ("http", 308, {"Range": f"bytes=0-{GCSBackend.UPLOAD_CHUNK - 1}"}),
        ("ok", b"{}"),                                         # final chunk
    ])
    _gcs(transport).write("ckpt.bin", b"z" * size)
    assert "uploadType=resumable" in transport.requests[0].full_url
    chunk1, chunk2 = transport.requests[1], transport.requests[2]
    assert chunk1.full_url == "https://gcs/session-123"
    assert chunk1.get_header("Content-range") == \
        f"bytes 0-{GCSBackend.UPLOAD_CHUNK - 1}/{size}"
    assert chunk2.get_header("Content-range") == \
        f"bytes {GCSBackend.UPLOAD_CHUNK}-{size - 1}/{size}"


def test_gcs_resumable_chunk_retries_on_503():
    from tpu_task.storage.backends import GCSBackend

    size = GCSBackend.RESUMABLE_THRESHOLD + 1
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-9"}),
        ("http", 308, {"Range": f"bytes=0-{GCSBackend.UPLOAD_CHUNK - 1}"}),
        ("http", 503),        # final chunk fails once
        ("ok", b"{}"),        # retried fine
    ])
    _gcs(transport).write("ckpt.bin", b"z" * size)
    assert len(transport.requests) == 4


def test_gcs_final_chunk_308_no_progress_is_an_error():
    """A 308 on the FINAL chunk that never advances means the object never
    finalized — it must raise, not silently succeed (ADVICE r2 medium)."""
    from tpu_task.storage.backends import GCSBackend

    chunk = GCSBackend.UPLOAD_CHUNK
    size = GCSBackend.RESUMABLE_THRESHOLD + 1
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-1"}),
        ("http", 308, {"Range": f"bytes=0-{chunk - 1}"}),  # chunk 1 committed
        ("http", 308, {"Range": f"bytes=0-{chunk - 1}"}),  # final: no progress
        ("http", 308, {"Range": f"bytes=0-{chunk - 1}"}),  # resent: still none
    ])
    with pytest.raises(RuntimeError, match="stalled"):
        _gcs(transport).write("ckpt.bin", b"z" * size)


def test_gcs_final_chunk_308_with_progress_resends_gap():
    """A 308 on the final chunk whose Range shows the server behind resends
    from the committed offset instead of aborting the whole session."""
    backend = _gcs(FakeTransport([]))
    backend.UPLOAD_CHUNK = 4
    backend.RESUMABLE_THRESHOLD = 4
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-5"}),
        ("http", 308, {"Range": "bytes=0-3"}),   # chunk 1 fully committed
        ("http", 308, {"Range": "bytes=0-7"}),   # chunk 2 fully committed
        ("http", 308, {"Range": "bytes=0-8"}),   # final PUT only half landed
        ("ok", b"{}"),                            # gap resent → finalized
    ])
    backend._urlopen = transport
    backend.write("ckpt.bin", b"abcdefghij")
    ranges = [r.get_header("Content-range") for r in transport.requests[1:]]
    assert ranges == ["bytes 0-3/10", "bytes 4-7/10", "bytes 8-9/10",
                      "bytes 9-9/10"]
    assert transport.requests[4].data == b"j"


def test_gcs_intermediate_308_range_behind_resends_gap():
    """When a retried chunk leaves the server's persisted offset behind, the
    Range header governs: the next PUT resends from the committed offset."""
    from tpu_task.storage.backends import GCSBackend

    backend = _gcs(FakeTransport([]))
    backend.UPLOAD_CHUNK = 4
    backend.RESUMABLE_THRESHOLD = 4
    data = b"abcdefghij"  # 10 bytes → chunks of 4
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-2"}),
        # chunk bytes 0-3 sent, but server only committed 0-1:
        ("http", 308, {"Range": "bytes=0-1"}),
        # resent from offset 2 (bytes 2-5), all committed:
        ("http", 308, {"Range": "bytes=0-5"}),
        # bytes 6-9 = final chunk, 2xx finalizes:
        ("ok", b"{}"),
    ])
    backend._urlopen = transport
    backend.write("ckpt.bin", data)
    ranges = [r.get_header("Content-range") for r in transport.requests[1:]]
    assert ranges == ["bytes 0-3/10", "bytes 2-5/10", "bytes 6-9/10"]
    assert transport.requests[2].data == b"cdef"


def test_gcs_resumable_stall_raises():
    """308s whose Range stops advancing get one resend, then a hard error —
    never an infinite loop."""
    backend = _gcs(FakeTransport([]))
    backend.UPLOAD_CHUNK = 4
    backend.RESUMABLE_THRESHOLD = 4
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-3"}),
        ("http", 308, {"Range": "bytes=0-1"}),   # committed offset 2
        ("http", 308, {"Range": "bytes=0-1"}),   # no progress → resend once
        ("http", 308, {"Range": "bytes=0-1"}),   # still none → stalled
    ])
    backend._urlopen = transport
    with pytest.raises(RuntimeError, match="stalled"):
        backend.write("ckpt.bin", b"abcdefghij")


def test_gcs_308_without_range_means_nothing_persisted():
    """Per the resumable protocol a 308 with NO Range header means zero bytes
    persisted — the client must resend the chunk, not advance past it."""
    backend = _gcs(FakeTransport([]))
    backend.UPLOAD_CHUNK = 4
    backend.RESUMABLE_THRESHOLD = 4
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-6"}),
        ("http", 308),                            # nothing persisted
        ("http", 308, {"Range": "bytes=0-3"}),    # resend landed
        ("http", 308, {"Range": "bytes=0-7"}),
        ("ok", b"{}"),
    ])
    backend._urlopen = transport
    backend.write("ckpt.bin", b"abcdefghij")
    ranges = [r.get_header("Content-range") for r in transport.requests[1:]]
    assert ranges == ["bytes 0-3/10", "bytes 0-3/10", "bytes 4-7/10",
                      "bytes 8-9/10"]


def test_gcs_write_from_file_streams_chunks(tmp_path):
    """write_from_file drives the resumable protocol straight off disk —
    correct Content-Range sequence, bodies read per-chunk."""
    backend = _gcs(FakeTransport([]))
    backend.UPLOAD_CHUNK = 4
    backend.RESUMABLE_THRESHOLD = 4
    path = tmp_path / "ckpt.bin"
    path.write_bytes(b"abcdefghij")
    transport = FakeTransport([
        ("ok", b"", {"Location": "https://gcs/session-4"}),
        ("http", 308, {"Range": "bytes=0-3"}),
        ("http", 308, {"Range": "bytes=0-7"}),
        ("ok", b"{}"),
    ])
    backend._urlopen = transport
    backend.write_from_file("ckpt.bin", str(path))
    bodies = [r.data for r in transport.requests[1:]]
    assert bodies == [b"abcd", b"efgh", b"ij"]


def test_gcs_read_to_file_parallel_ranged_download(tmp_path):
    """Large downloads fetch parallel ranged chunks and assemble in place."""
    backend = _gcs(FakeTransport([]))
    backend.DOWNLOAD_CHUNK = 4
    backend.DOWNLOAD_WORKERS = 1  # deterministic order for the scripted fake
    content = b"abcdefghij"
    transport = FakeTransport([
        ("ok", json.dumps({"size": str(len(content))}).encode()),  # size probe
        ("ok", content[0:4]),
        ("ok", content[4:8]),
        ("ok", content[8:10]),
    ])
    backend._urlopen = transport
    out = tmp_path / "restored.bin"
    backend.read_to_file("ckpt.bin", str(out))
    assert out.read_bytes() == content
    range_headers = [r.get_header("Range") for r in transport.requests[1:]]
    assert range_headers == ["bytes=0-3", "bytes=4-7", "bytes=8-9"]


def test_gcs_read_to_file_small_object_single_get(tmp_path):
    backend = _gcs(FakeTransport([]))
    content = b"tiny"
    transport = FakeTransport([
        ("ok", json.dumps({"size": str(len(content))}).encode()),
        ("ok", content),
    ])
    backend._urlopen = transport
    out = tmp_path / "small.bin"
    backend.read_to_file("k", str(out))
    assert out.read_bytes() == content


def test_gcs_expired_token_mid_lifecycle():
    """401 on a read → token invalidated, refetched, request replayed."""
    from tpu_task.storage.backends import GCSBackend

    tokens = iter([("old", 3600.0), ("new", 3600.0)])
    backend = GCSBackend("bkt")
    backend._token._fetch = lambda: next(tokens)
    transport = FakeTransport([("http", 401), ("ok", b"data")])
    backend._urlopen = transport
    backend._sleep = FakeSleep()
    assert backend.read("k") == b"data"
    assert transport.requests[1].get_header("Authorization") == "Bearer new"


# -- S3 / Azure through the fake transport ------------------------------------


def test_s3_request_retries_5xx():
    from tpu_task.storage.cloud_backends import S3Backend

    backend = S3Backend("bkt", config={"access_key_id": "AK",
                                       "secret_access_key": "SK"})
    transport = FakeTransport([("http", 503), ("ok", b"data")])
    backend._urlopen = transport
    backend._sleep = FakeSleep()
    assert backend.read("k") == b"data"
    assert len(transport.requests) == 2


def test_azure_request_retries_connection_error():
    from tpu_task.storage.cloud_backends import AzureBlobBackend

    backend = AzureBlobBackend(
        "ctr", config={"account": "acct", "key": "a2V5"})
    transport = FakeTransport([("conn",), ("ok", b"data")])
    backend._urlopen = transport
    backend._sleep = FakeSleep()
    assert backend.read("k") == b"data"
    assert len(transport.requests) == 2


# -- Cloud TPU REST client through the fake transport -------------------------


def _tpu(transport):
    from tpu_task.backends.tpu.api import RestTpuClient

    client = RestTpuClient("proj", "us-central2-b")
    client._token._fetch = lambda: ("tok", 3600.0)
    client._urlopen = transport
    client._sleep = FakeSleep()
    return client


def test_tpu_client_retries_5xx():
    transport = FakeTransport([
        ("http", 500),
        ("ok", json.dumps({"state": {"state": "ACTIVE"},
                           "tpu": {"nodeSpec": []}}).encode()),
    ])
    info = _tpu(transport).get_queued_resource("qr-1")
    assert info.state == "ACTIVE"
    assert len(transport.requests) == 2


def test_tpu_client_409_is_idempotent_create():
    from tpu_task.backends.tpu.api import QueuedResourceSpec

    transport = FakeTransport([("http", 409)])
    _tpu(transport).create_queued_resource(
        "qr-1", QueuedResourceSpec(node_id="n", accelerator_type="v4-8",
                                   runtime_version="tpu-ubuntu2204-base"))
    assert len(transport.requests) == 1  # no crash, no retry loop


def test_tpu_client_token_refresh_on_401():
    from tpu_task.backends.tpu.api import RestTpuClient

    tokens = iter([("stale", 3600.0), ("fresh", 3600.0)])
    client = RestTpuClient("proj", "us-central2-b")
    client._token._fetch = lambda: next(tokens)
    transport = FakeTransport([
        ("http", 401),
        ("ok", json.dumps({"nodes": []}).encode()),
    ])
    client._urlopen = transport
    client._sleep = FakeSleep()
    assert client.list_nodes() == []
    assert transport.requests[1].get_header("Authorization") == "Bearer fresh"


def test_tpu_get_parses_full_node_spec():
    """The QR GET echoes the complete node spec; the client must parse it all
    back so recovery can re-queue from the API record alone (a bare `read`
    holds no local spec). Regression for the r2 sparse-parse bug."""
    payload = {
        "state": {"state": "SUSPENDED"},
        "tpu": {"nodeSpec": [{
            "nodeId": "qr-1",
            "node": {
                "acceleratorType": "v5litepod-16",
                "runtimeVersion": "v2-alpha-tpuv5-lite",
                "metadata": {"startup-script": "#!/bin/bash\necho hi",
                             "tpu-task-env-FOO": "bar"},
                "labels": {"team": "ml"},
                "schedulingConfig": {"preemptible": True},
                "serviceAccount": {"email": "sa@proj.iam.gserviceaccount.com"},
                "networkConfig": {"network": "projects/p/global/networks/custom"},
            },
        }]},
    }
    transport = FakeTransport([("ok", json.dumps(payload).encode())])
    info = _tpu(transport).get_queued_resource("qr-1")
    assert info.state == "SUSPENDED"
    spec = info.spec
    assert spec.accelerator_type == "v5litepod-16"
    assert spec.runtime_version == "v2-alpha-tpuv5-lite"
    assert spec.startup_script == "#!/bin/bash\necho hi"
    assert "startup-script" not in spec.metadata
    assert spec.metadata["tpu-task-env-FOO"] == "bar"
    assert spec.labels == {"team": "ml"}
    assert spec.spot is True
    assert spec.service_account == "sa@proj.iam.gserviceaccount.com"
    assert spec.network == "projects/p/global/networks/custom"


# -- parallel cloud copy ------------------------------------------------------


class MemoryBackend:
    """Minimal non-local Backend double (local_root None → cloud path)."""

    def __init__(self):
        self.objects = {}

    def list(self, prefix=""):
        return sorted(k for k in self.objects if k.startswith(prefix))

    def list_meta(self, prefix=""):
        return {k: (len(v), 0.0) for k, v in self.objects.items()
                if k.startswith(prefix)}

    def listdirs(self):
        return []

    def makedir(self, key):
        pass

    def read(self, key):
        return self.objects[key]

    def write(self, key, data):
        self.objects[key] = data

    def delete(self, key):
        self.objects.pop(key, None)

    def exists(self):
        return True

    def local_root(self):
        return None


def test_parallel_cloud_copy_moves_every_file():
    from tpu_task.storage.sync import _copy_files

    src, dst = MemoryBackend(), MemoryBackend()
    keys = [f"f{i:03d}" for i in range(40)]
    for key in keys:
        src.objects[key] = key.encode()
    _copy_files(src, dst, keys)
    assert dst.objects == src.objects


def test_parallel_copy_propagates_worker_errors():
    from tpu_task.storage.sync import _copy_files

    src, dst = MemoryBackend(), MemoryBackend()
    for i in range(10):
        src.objects[f"f{i}"] = b"x"

    boom = RuntimeError("copy failed")

    class FailingDst(MemoryBackend):
        def write(self, key, data):
            if key == "f7":
                raise boom
            super().write(key, data)

    dst = FailingDst()
    with pytest.raises(RuntimeError, match="copy failed"):
        _copy_files(src, dst, sorted(src.objects))
