"""SLA brownout soak (`make sla-soak`): sustained 2x overload plus a
mid-wave replica preemption through the whole actuation plane — router
deadlines/classes, degrade ladder, bounded replica admission (429 +
Retry-After), scheduler requeue — asserting the brownout CONTRACT:

* premium p99 TTFT holds within its SLO through the overload;
* best_effort sheds first and sheds MORE as load grows (monotone);
* shed is a durable terminal (structured error + Retry-After, never
  resurrected by later pumps);
* the scheduler's fairness invariants hold throughout (any
  SchedulerInvariantError/PoolInvariantError raised by the control loop
  fails the test).

Replayable via TPU_TASK_CHAOS_SEED, same contract as the serve soak.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpu_task.obs import DegradeLadder
from tpu_task.scheduler import CapacityPool, GangScheduler, TenantQuota
from tpu_task.serve import (
    InProcessServeDriver,
    ReplicaServer,
    Router,
    ServeFleet,
    ServeSpec,
    wait_until,
)

pytestmark = [pytest.mark.sla, pytest.mark.chaos, pytest.mark.slow]

SEED = int(os.environ.get("TPU_TASK_CHAOS_SEED", "20260804"))
MAX_NEW = 32


def _post(url, payload=None, headers=None):
    data = json.dumps(payload or {}).encode()
    request = urllib.request.Request(url, data=data, method="POST",
                                     headers=headers or {})
    with urllib.request.urlopen(request, timeout=10) as response:
        return json.loads(response.read().decode())


def test_replica_answers_429_with_retry_after_when_full_or_draining():
    """Satellite-1 replica side: a full or draining replica answers 429
    + ``Retry-After: 0`` with a structured body — never a bare 409 the
    router would have to guess about."""
    server = ReplicaServer(preset="micro", max_queue=0).start()
    try:
        # max_queue=0: every admission is over the bound.
        with pytest.raises(urllib.error.HTTPError) as info:
            _post(f"{server.url}/submit",
                  {"prompt": [1, 2, 3], "max_new_tokens": 4})
        assert info.value.code == 429
        assert info.value.headers["Retry-After"] == "0"
        assert json.loads(info.value.read().decode())["overloaded"]

        _post(f"{server.url}/drain")
        with pytest.raises(urllib.error.HTTPError) as info:
            _post(f"{server.url}/submit",
                  {"prompt": [1, 2, 3], "max_new_tokens": 4})
        assert info.value.code == 429
        assert info.value.headers["Retry-After"] == "0"
        assert json.loads(info.value.read().decode())["draining"]
    finally:
        server.stop()


def _build_fleet(replicas: int):
    driver = InProcessServeDriver()
    scheduler = GangScheduler(
        CapacityPool([4 * replicas]),
        {"sla": TenantQuota(chips=4 * replicas, weight=1.0)}, driver)
    router = Router(seed=SEED, ladder=DegradeLadder(clamp_max_new=8))
    fleet = ServeFleet(
        scheduler,
        ServeSpec(service="sla", tenant="sla", replicas=replicas,
                  preset="micro",
                  serving={"slots": 4, "max_queue": 8}),
        router)
    fleet.launch()
    assert wait_until(lambda: len(fleet.refresh_endpoints()) == replicas,
                      120, tick=fleet.tick, period=0.05)
    fleet.tick()
    warm = [router.submit(np.zeros(4, np.int32), 2)
            for _ in range(replicas * 4)]
    router.drain(deadline_s=180, on_idle=fleet.tick)
    del warm
    return driver, scheduler, router, fleet


def _teardown(driver):
    for task_id in list(driver.running_ids()):
        driver._stop(task_id, graceful=False)


def _run_wave(load: float, *, preempt: bool = False) -> dict:
    """One soak wave at ``load`` x the calibrated service rate through a
    2-replica fleet; optionally kills one replica a third of the way in
    (the preemption wave) and requires it restored before exit."""
    driver, scheduler, router, fleet = _build_fleet(2)
    try:
        rng = np.random.default_rng(SEED)
        t0 = time.monotonic()
        timed = [router.submit(
            rng.integers(0, 256, size=8).astype(np.int32), MAX_NEW)
            for _ in range(8)]
        router.drain(deadline_s=180, on_idle=fleet.tick)
        del timed
        # Per-request service at full concurrency across the 2-replica
        # fleet; deadlines and the beat cadence scale from it (same
        # calibration scheme as `bench.py fleet --overload`).
        service_s = max((time.monotonic() - t0) / 8, 1e-3)
        deadline_ms = 14.0 * service_s * 1000.0
        beat_s = max(0.02, 2.0 * service_s)

        n_requests = 40
        work, t = [], 0.0
        for i in range(n_requests):
            t += float(rng.exponential(service_s / load))
            work.append({
                "arrival": t,
                "prompt": rng.integers(0, 256, size=8).astype(np.int32),
                "slo_class": "premium" if i % 2 == 0 else "best_effort",
            })

        t0 = time.monotonic()
        fids, i = {}, 0
        last_beat, last_bad = t0, 0
        killed_at = restored_at = victim = None
        while True:
            now = time.monotonic()
            while i < len(work) and work[i]["arrival"] <= now - t0:
                fids[i] = router.submit(
                    work[i]["prompt"], MAX_NEW,
                    slo_class=work[i]["slo_class"],
                    deadline_ms=deadline_ms)
                i += 1
            open_count = router.pump(wait_ms=0)
            fleet.tick()
            if preempt and killed_at is None and i >= n_requests // 3:
                live = [fid for fid in fids.values()
                        if router.request(fid).status == "running"
                        and router.request(fid).replica]
                if live:
                    victim = router.request(live[0]).replica
                    driver.kill(victim, graceful=True)
                    killed_at = now
            if killed_at and restored_at is None and victim in \
                    fleet.refresh_endpoints():
                restored_at = now
            if now - last_beat >= beat_s:
                bad = sum(c["missed"] + c["shed"]
                          for c in router.stats()["sla"]
                          ["classes"].values())
                router.note_alerts(["burn"] if bad > last_bad else [])
                last_bad, last_beat = bad, now
            if i == len(work) and open_count == 0 and (
                    not preempt or restored_at is not None):
                break
            if now - t0 > 300:
                raise RuntimeError("soak wave did not converge")

        sla = router.stats()["sla"]
        ttft = {
            cls: sorted(
                request.first_token_t - request.submit_t
                for j, fid in fids.items()
                if work[j]["slo_class"] == cls
                and (request := router.request(fid)).first_token_t
                is not None)
            for cls in ("premium", "best_effort")
        }
        shed_fids = [fid for fid in fids.values()
                     if router.request(fid).status == "shed"]
        # Durable terminals: a shed request raises a structured error
        # with its Retry-After and never resurrects on later pumps.
        for fid in shed_fids[:3]:
            assert router.request(fid).retry_after_s is not None
            with pytest.raises(RuntimeError, match="shed"):
                router.result(fid)
        router.pump(wait_ms=0)
        assert all(router.request(fid).status == "shed"
                   for fid in shed_fids)
        return {
            "deadline_s": deadline_ms / 1000.0,
            "classes": sla["classes"],
            "ttft": ttft,
            "sheds": {cls: sla["classes"].get(
                cls, {"shed": 0})["shed"]
                for cls in ("premium", "best_effort")},
        }
    finally:
        _teardown(driver)


def test_sla_brownout_soak_premium_holds_while_best_effort_sheds():
    calm = _run_wave(1.0)
    storm = _run_wave(2.0, preempt=True)

    # Premium p99 TTFT within the SLO through overload + preemption.
    for wave in (calm, storm):
        p99 = wave["ttft"]["premium"][
            max(0, int(len(wave["ttft"]["premium"]) * 0.99) - 1)]
        assert p99 <= wave["deadline_s"], \
            f"premium p99 TTFT {p99:.3f}s blew the " \
            f"{wave['deadline_s']:.3f}s SLO"

    # The brownout routes pain down the class ladder, never up it.
    for wave in (calm, storm):
        prem = wave["classes"].get("premium", {})
        best = wave["classes"].get("best_effort", {})
        assert best.get("attainment", 1.0) <= \
            prem.get("attainment", 1.0) + 1e-9
        assert prem.get("shed", 0) <= best.get("shed", 0)

    # Best_effort sheds monotonically with load.
    assert calm["sheds"]["best_effort"] <= storm["sheds"]["best_effort"]
    # The storm actually browned out (the wave was not a no-op).
    assert storm["sheds"]["best_effort"] + sum(
        c.get("missed", 0) for c in storm["classes"].values()) > 0
