"""Data-plane tests, mirroring the reference's hermetic local-backend strategy
(reference: task/common/machine/storage_test.go:15-119)."""

import json
import os
import time

import pytest

from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.values import StatusCode
from tpu_task.storage import (
    Connection,
    delete_storage,
    limit_transfer,
    logs,
    status,
    sync,
    transfer,
)


# --- connection strings (storage_test.go:15-53 vectors) ---------------------

def test_connection_string_with_config():
    conn = Connection(
        backend="azureblob", container="container",
        config={"account": "az_account", "key": "az_key"},
    )
    assert str(conn) == ":azureblob,account='az_account',key='az_key':container"


def test_connection_string_with_path():
    conn = Connection(backend="azureblob", container="container", path="/subdirectory")
    assert str(conn) == ":azureblob:container/subdirectory"


def test_connection_string_path_without_separator():
    conn = Connection(backend="azureblob", container="container", path="subdirectory")
    assert str(conn) == ":azureblob:container/subdirectory"


def test_connection_string_parse_roundtrip():
    conn = Connection(
        backend="googlecloudstorage", container="bucket", path="/sub",
        config={"service_account_credentials": '{"a": "b,c"}'},
    )
    parsed = Connection.parse(str(conn))
    assert parsed.backend == conn.backend
    assert parsed.container == conn.container
    assert parsed.path == conn.path
    assert parsed.config == conn.config


def test_connection_parse_local_path():
    conn = Connection.parse("/some/dir")
    assert conn.backend == "local"
    assert conn.path == "/some/dir"


# --- transfer filter semantics (storage_test.go:55-101) ---------------------

@pytest.fixture
def fixture_tree(tmp_path):
    src = tmp_path / "src"
    (src / "temp").mkdir(parents=True)
    (src / "main.tf").write_text("terraform config — must never transfer")
    (src / "a.txt").write_text("root a")
    (src / "temp" / "a.txt").write_text("nested a")
    (src / "temp" / "b.txt").write_text("nested b")
    return str(src)


def list_tree(root):
    entries = []
    for dirpath, dirnames, filenames in os.walk(root):
        for name in dirnames + filenames:
            full = os.path.join(dirpath, name)
            entries.append("/" + os.path.relpath(full, root).replace(os.sep, "/"))
    return sorted(entries)


def test_builtin_excludes_terraform_files(fixture_tree, tmp_path):
    dst = tmp_path / "dst1"
    transfer(fixture_tree, str(dst))
    assert list_tree(dst) == ["/a.txt", "/temp", "/temp/a.txt", "/temp/b.txt"]


def test_glob_exclude_keeps_directories(fixture_tree, tmp_path):
    dst = tmp_path / "dst2"
    transfer(fixture_tree, str(dst), exclude=["**.txt"])
    assert list_tree(dst) == ["/temp"]  # directory still gets transferred


def test_explicitly_anchored_exclude(fixture_tree, tmp_path):
    dst = tmp_path / "dst3"
    transfer(fixture_tree, str(dst), exclude=["/a.txt"])
    assert list_tree(dst) == ["/temp", "/temp/a.txt", "/temp/b.txt"]


def test_implicitly_anchored_exclude(fixture_tree, tmp_path):
    dst = tmp_path / "dst4"
    transfer(fixture_tree, str(dst), exclude=["a.txt"])
    assert list_tree(dst) == ["/temp", "/temp/a.txt", "/temp/b.txt"]


def test_transfer_preserves_contents(fixture_tree, tmp_path):
    dst = tmp_path / "dst5"
    transfer(fixture_tree, str(dst))
    assert (dst / "temp" / "b.txt").read_text() == "nested b"


# --- sync (mirror) semantics ------------------------------------------------

def test_sync_removes_extraneous(fixture_tree, tmp_path):
    dst = tmp_path / "dst6"
    dst.mkdir()
    (dst / "stale.bin").write_text("left over from a previous epoch")
    sync(fixture_tree, str(dst))
    assert "/stale.bin" not in list_tree(dst)
    assert "/a.txt" in list_tree(dst)


def test_sync_roundtrip_restore(fixture_tree, tmp_path):
    """Workdir → bucket → fresh workdir (the preemption-recovery restore path)."""
    bucket = tmp_path / "bucket" / "data"
    restored = tmp_path / "restored"
    sync(fixture_tree, str(bucket))
    sync(str(bucket), str(restored))
    assert (restored / "temp" / "a.txt").read_text() == "nested a"


# --- limit_transfer (storage.go:265-280) ------------------------------------

def test_limit_transfer_rules():
    rules = limit_transfer("output", ["- cache/**"])
    assert rules == ["- cache/**", "+ /output", "+ /output/**", "- /**"]


def test_limit_transfer_noop_for_root():
    assert limit_transfer("", ["- x"]) == ["- x"]
    assert limit_transfer(".", ["- x"]) == ["- x"]


def test_limit_transfer_end_to_end(fixture_tree, tmp_path):
    dst = tmp_path / "dst7"
    transfer(fixture_tree, str(dst), exclude=limit_transfer("temp", []))
    assert list_tree(dst) == ["/temp", "/temp/a.txt", "/temp/b.txt"]


# --- mailbox protocol: reports / logs / status ------------------------------

@pytest.fixture
def mailbox(tmp_path):
    remote = tmp_path / "bucket"
    (remote / "reports").mkdir(parents=True)
    return remote


def test_logs_reads_task_reports(mailbox):
    (mailbox / "reports" / "task-machine1").write_text("line one\nline two\n")
    (mailbox / "reports" / "task-machine2").write_text("other machine\n")
    (mailbox / "reports" / "status-machine1").write_text("{}")
    result = sorted(logs(str(mailbox)))
    assert result == ["line one\nline two\n", "other machine\n"]


def test_status_counts_exit_codes(mailbox):
    (mailbox / "reports" / "status-m1").write_text(
        json.dumps({"result": "exit-code", "code": "0", "status": "0"}))
    (mailbox / "reports" / "status-m2").write_text(
        json.dumps({"result": "exit-code", "code": "1", "status": "1"}))
    (mailbox / "reports" / "status-m3").write_text(
        json.dumps({"result": "timeout", "code": "", "status": ""}))
    result = status(str(mailbox), {StatusCode.ACTIVE: 3})
    assert result[StatusCode.ACTIVE] == 3
    assert result[StatusCode.SUCCEEDED] == 1
    assert result[StatusCode.FAILED] == 2


def test_status_uppercase_keys(mailbox):
    """Go's encoding/json matches keys case-insensitively; so do we."""
    (mailbox / "reports" / "status-m1").write_text('{"Code": "0"}')
    assert status(str(mailbox))[StatusCode.SUCCEEDED] == 1


def test_status_malformed_report_skipped_with_warning(mailbox, caplog):
    """One corrupt blob (torn write, flaky store) must not kill the whole
    poll tick: it is skipped with a warning and the rest still count."""
    import logging

    (mailbox / "reports" / "status-m1").write_text("not json")
    with caplog.at_level(logging.WARNING, logger="tpu_task"):
        assert status(str(mailbox)) == {}
    assert any("malformed status report" in record.message
               for record in caplog.records)


def test_status_counts_healthy_reports_around_corrupt_one(mailbox):
    (mailbox / "reports" / "status-m1").write_text(
        json.dumps({"result": "exit-code", "code": "0", "status": "0"}))
    (mailbox / "reports" / "status-m2").write_text("{{{ torn write")
    (mailbox / "reports" / "status-m3").write_text(
        json.dumps({"result": "exit-code", "code": "1", "status": "1"}))
    (mailbox / "reports" / "status-m4").write_text(
        json.dumps([1, 2, 3]))  # valid JSON, wrong shape: also skipped
    result = status(str(mailbox))
    assert result[StatusCode.SUCCEEDED] == 1
    assert result[StatusCode.FAILED] == 1


# --- mtime-tolerance boundaries (the one named constant) ---------------------

def test_changed_keys_mtime_tolerance_boundaries():
    """Exactly-at-tolerance differences are up-to-date; just-beyond are
    changed. Object stores (mtimes not preserved) list the UPLOAD time,
    always later than the source mtime — only a source newer than the
    stored copy re-uploads (the rclone caveat)."""
    import importlib

    sync_mod = importlib.import_module("tpu_task.storage.sync")
    tol = sync_mod.MTIME_TOLERANCE

    src = {"a": (10, 100.0)}
    # Preserved mtimes (local↔local): a difference inside the tolerance
    # (filesystem granularity) is up-to-date; beyond it — either
    # direction — is changed. Margins at tol/2 and 1.5*tol keep the
    # assertions float-rounding-proof.
    within = {"a": (10, 100.0 + tol / 2)}
    beyond = {"a": (10, 100.0 + tol * 1.5)}
    behind = {"a": (10, 100.0 - tol * 1.5)}
    assert sync_mod._changed_keys(["a"], src, within, True) == []
    assert sync_mod._changed_keys(["a"], src, beyond, True) == ["a"]
    assert sync_mod._changed_keys(["a"], src, behind, True) == ["a"]
    # Object store (upload time always later than the source mtime): a
    # later dst is up-to-date — a HUGE skew must not re-upload; dst behind
    # src within tolerance is up-to-date; behind by more means the source
    # was touched since the upload.
    later = {"a": (10, 150.0)}
    within_behind = {"a": (10, 100.0 - tol / 2)}
    stale = {"a": (10, 100.0 - tol * 1.5)}
    assert sync_mod._changed_keys(["a"], src, later, False) == []
    assert sync_mod._changed_keys(["a"], src, within_behind, False) == []
    assert sync_mod._changed_keys(["a"], src, stale, False) == ["a"]
    # A size difference always wins, regardless of mtimes.
    resized = {"a": (11, 150.0)}
    assert sync_mod._changed_keys(["a"], src, resized, False) == ["a"]


def test_reports_fans_out_cloud_reads_in_parallel(monkeypatch):
    """A cloud-backed status poll of an N-worker pod must not be N serial
    round-trips: reads fan out over the transfer pool, and the result keeps
    the listing's deterministic order regardless of completion order."""
    import importlib
    import threading

    sync_module = importlib.import_module("tpu_task.storage.sync")

    class SlowCloudBackend:
        def __init__(self, blobs):
            self.blobs = blobs
            self.in_flight = 0
            self.max_in_flight = 0
            self._lock = threading.Lock()

        def list(self, prefix=""):
            return sorted(k for k in self.blobs if k.startswith(prefix))

        def read(self, key):
            with self._lock:
                self.in_flight += 1
                self.max_in_flight = max(self.max_in_flight, self.in_flight)
            time.sleep(0.02)
            with self._lock:
                self.in_flight -= 1
            return self.blobs[key]

        def local_root(self):
            return None  # cloud store → parallel path

    backend = SlowCloudBackend(
        {f"reports/status-m{i:02d}": f"report {i}".encode()
         for i in range(8)})
    monkeypatch.setattr(sync_module, "open_backend",
                        lambda remote: (backend, None))
    out = sync_module.reports(":googlecloudstorage:bkt", "status")
    assert out == [f"report {i}" for i in range(8)]  # sorted-key order
    assert backend.max_in_flight > 1  # genuinely concurrent


def test_delete_storage(mailbox):
    (mailbox / "reports" / "task-m1").write_text("x")
    (mailbox / "data").mkdir()
    (mailbox / "data" / "f").write_text("y")
    delete_storage(str(mailbox))
    assert os.listdir(mailbox) == []


def test_delete_missing_storage_raises(tmp_path):
    with pytest.raises(ResourceNotFoundError):
        delete_storage(str(tmp_path / "never-created"))


# --- native core ------------------------------------------------------------

def test_native_copy_core(tmp_path):
    from tpu_task.storage import native

    pairs = []
    for index in range(20):
        src = tmp_path / f"src{index}.bin"
        src.write_bytes(os.urandom(1000 * index))
        pairs.append((str(src), str(tmp_path / "out" / f"dst{index}.bin")))
    available = native.copy_files(pairs, threads=4)
    if not available:
        pytest.skip("native toolchain unavailable")
    for index, (src, dst) in enumerate(pairs):
        with open(src, "rb") as a, open(dst, "rb") as b:
            assert a.read() == b.read()


def test_incremental_sync_skips_up_to_date(tmp_path, monkeypatch):
    """Second sync of an unchanged tree copies nothing (rclone's
    size+modtime check); a touched file is re-copied."""
    import importlib

    # The package attribute `sync` is the function (shadowing the module);
    # go through importlib for the module object.
    sync_mod = importlib.import_module("tpu_task.storage.sync")
    sync = sync_mod.sync

    src = tmp_path / "src"
    dst = tmp_path / "dst"
    src.mkdir()
    (src / "a.txt").write_text("alpha")
    (src / "sub").mkdir()
    (src / "sub" / "b.txt").write_text("beta")

    sync(str(src), str(dst))
    assert (dst / "sub" / "b.txt").read_text() == "beta"

    copied = []
    real = sync_mod._copy_files

    def spy(source, destination, keys, src_meta=None):
        copied.extend(keys)
        return real(source, destination, keys, src_meta)

    monkeypatch.setattr(sync_mod, "_copy_files", spy)
    sync(str(src), str(dst))
    assert copied == []            # nothing changed → nothing copied

    time.sleep(0.01)
    (src / "a.txt").write_text("ALPHA")
    sync(str(src), str(dst))
    assert copied == ["a.txt"]     # only the touched file
    assert (dst / "a.txt").read_text() == "ALPHA"


def test_native_copy_preserves_mtime(tmp_path):
    from tpu_task.storage import native

    src = tmp_path / "x.bin"
    src.write_bytes(b"data")
    os.utime(src, (1000000000, 1000000000))
    dst = tmp_path / "out" / "x.bin"
    if not native.copy_files([(str(src), str(dst))]):
        pytest.skip("native toolchain unavailable")
    assert abs(os.path.getmtime(dst) - 1000000000) < 0.01


def test_worker0_mirror_spares_other_workers_shards(tmp_path):
    """The worker-0 agent mirror excludes other workers' checkpoint shard
    files, so its sync cannot delete shards only worker N uploaded
    (tpu-worker-script.sh.tpl data loop rules)."""
    src = tmp_path / "workdir"
    (src / "checkpoints").mkdir(parents=True)
    (src / "checkpoints" / "ckpt-5.shard-0.npz").write_bytes(b"w0")
    (src / "data.txt").write_text("payload")
    dst = tmp_path / "bucket-data"
    (dst / "checkpoints").mkdir(parents=True)
    (dst / "checkpoints" / "ckpt-5.shard-1.npz").write_bytes(b"w1")
    (dst / "stale.txt").write_text("old")

    sync(str(src), str(dst), exclude=["+ **ckpt-*.shard-0.*",
                                      "- **ckpt-*.shard-*"])
    # Worker 0's own shard and files mirrored; worker 1's shard SURVIVES;
    # genuinely stale files still deleted.
    assert (dst / "checkpoints" / "ckpt-5.shard-0.npz").read_bytes() == b"w0"
    assert (dst / "checkpoints" / "ckpt-5.shard-1.npz").read_bytes() == b"w1"
    assert (dst / "data.txt").read_text() == "payload"
    assert not (dst / "stale.txt").exists()


def test_local_write_if_absent_race_single_winner(tmp_path):
    """N threads racing the same key: exactly one write wins (O_EXCL), and
    the record is never a torn mix — the property durable recovery events
    rely on for concurrent observers."""
    import threading

    from tpu_task.storage.backends import LocalBackend

    backend = LocalBackend(str(tmp_path))
    winners = []
    barrier = threading.Barrier(8)

    def attempt(i):
        barrier.wait()
        if backend.write_if_absent("events/e.json", f"writer-{i}".encode() * 64):
            winners.append(i)

    threads = [threading.Thread(target=attempt, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(winners) == 1
    content = backend.read("events/e.json")
    assert content == f"writer-{winners[0]}".encode() * 64


def test_for_each_fails_fast_and_cancels_queued_work():
    """The sync engine's parallel fan-out rides parallel_map's fail-fast
    drain: the first worker exception re-raises and still-queued sibling
    transfers are cancelled instead of streaming to completion."""
    import importlib
    import threading
    import time as _time

    # tpu_task.storage exports sync the FUNCTION; fetch the module.
    sync_mod = importlib.import_module("tpu_task.storage.sync")

    done = []
    done_lock = threading.Lock()

    def work(key):
        if key == "k-fail":
            raise OSError("simulated transfer failure")
        _time.sleep(0.3)
        with done_lock:
            done.append(key)

    keys = ["k-fail"] + [f"k{i}" for i in range(8)]
    orig = sync_mod.CLOUD_COPY_WORKERS
    sync_mod.CLOUD_COPY_WORKERS = 2
    try:
        with pytest.raises(OSError, match="simulated transfer failure"):
            sync_mod._for_each(work, keys, parallel=True)
    finally:
        sync_mod.CLOUD_COPY_WORKERS = orig
    # 2 workers: the failure + at most one in-flight sibling ran; the other
    # 7 queued transfers were cancelled by the fail-fast drain.
    assert len(done) <= 2
