"""TPU REST and EC2/ASG Query control planes end-to-end over real HTTP.

The unit suites (test_tpu_backend.py, test_aws_real.py) verify behavior
against injected in-process transports; these tests close VERDICT r3 weak
spot #1 by running the SAME lifecycles through real sockets — Bearer/SigV4
auth headers, the retry layer, JSON/XML parsing, and LRO operation polling
all execute against stateful loopback servers
(backends/tpu/emulator.py, backends/aws/emulator.py).
"""

import json

import pytest

from test_http_resilience import FakeSleep

from tpu_task.backends.aws.emulator import LoopbackAws
from tpu_task.backends.tpu.emulator import LoopbackTpu
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    Environment,
    Size,
    SPOT_ENABLED,
    StatusCode,
    Task as TaskSpec,
)


# -- TPU REST over HTTP --------------------------------------------------------


@pytest.fixture()
def tpu_client():
    from tpu_task.backends.tpu.api import RestTpuClient

    with LoopbackTpu() as server:
        client = RestTpuClient(project="proj", zone="us-central2-b")
        server.attach(client)
        yield server, client


def _qr_spec(**overrides):
    from tpu_task.backends.tpu.api import QueuedResourceSpec

    base = dict(
        node_id="node-0", accelerator_type="v4-16",
        runtime_version="tpu-ubuntu2204-base",
        startup_script="#!/bin/bash\necho boot\n",
        metadata={"tpu-task-remote": ":googlecloudstorage:bkt/task"},
        labels={"tpu-task": "1"}, spot=True,
    )
    base.update(overrides)
    return QueuedResourceSpec(**base)


def test_tpu_lifecycle_over_http(tpu_client, monkeypatch):
    """create (LRO polled) → get (full spec echo) → list → node → delete →
    404, with Bearer auth on every request."""
    server, client = tpu_client
    monkeypatch.setattr("time.sleep", lambda _s: None)  # LRO waiter pacing

    client.create_queued_resource("qr-0", _qr_spec())
    client.create_queued_resource("qr-0", _qr_spec())  # idempotent: 409 → ok

    info = client.get_queued_resource("qr-0")
    assert info.state == "ACTIVE"
    # The GET echoes the FULL created spec — what bare-read recovery needs.
    assert info.spec.startup_script == "#!/bin/bash\necho boot\n"
    assert info.spec.metadata["tpu-task-remote"] == \
        ":googlecloudstorage:bkt/task"
    assert info.spec.spot is True
    assert info.spec.accelerator_type == "v4-16"

    assert client.list_queued_resources() == ["qr-0"]
    node = client.get_node("node-0")
    assert node.state == "READY"
    assert node.worker_count == 2  # v4-16 → 2 hosts
    assert len(node.endpoints) == 2

    client.delete_queued_resource("qr-0")
    with pytest.raises(ResourceNotFoundError):
        client.get_queued_resource("qr-0")
    assert all(a.startswith("Bearer ") for a in server.auth_headers)


def test_tpu_client_rides_out_emulated_brownout(tpu_client, monkeypatch):
    """Chaos over real sockets: the emulator's ``fail_next`` brownout hook
    serves 503s/429s and the real client's retry ladder (pooled transport,
    full-jitter backoff) absorbs them — no injected transports anywhere."""
    server, client = tpu_client
    monkeypatch.setattr("time.sleep", lambda _s: None)
    client._sleep = lambda _s: None  # backoff pacing out of the wall-clock

    client.create_queued_resource("qr-b", _qr_spec(node_id="node-b"))
    server.fail_next(count=2, status=503)
    info = client.get_queued_resource("qr-b")   # 503, 503, then 200
    assert info.state == "ACTIVE"
    server.fail_next(count=1, status=429)
    assert client.list_queued_resources() == ["qr-b"]
    client.delete_queued_resource("qr-b")


def test_tpu_preemption_recovery_over_http(tpu_client, tmp_path, monkeypatch):
    """The flagship reconciler over real sockets: a bare-read TPUTask sees
    SUSPENDED, re-queues from the spec echoed by the API, and persists the
    durable recovery event — no injected transports anywhere."""
    from tpu_task.backends.tpu.task import TPUTask
    from tpu_task.common.cloud import Cloud, Credentials, GCPCredentials, Provider

    server, client = tpu_client
    monkeypatch.setattr("time.sleep", lambda _s: None)
    bucket = tmp_path / "bucket"
    bucket.mkdir()

    identifier = Identifier.deterministic("loopback-recover")
    name = f"{identifier.long()}-0"
    client.create_queued_resource(name, _qr_spec(
        node_id=name, metadata={"tpu-task-remote": str(bucket)}))
    server.preempt(name)

    cloud = Cloud(provider=Provider.TPU, region="us-central2-b",
                  credentials=Credentials(gcp=GCPCredentials(
                      application_credentials=json.dumps(
                          {"project_id": "proj"}))))
    task = TPUTask(cloud, identifier, TaskSpec())  # bare read: empty spec
    server.attach(task.client)

    task.read()
    assert server.qrs[name]["state"] == "ACTIVE"  # re-queued
    requeued = task.client.get_queued_resource(name)
    assert requeued.spec.startup_script == "#!/bin/bash\necho boot\n"
    assert requeued.spec.spot is True

    # Durable MTTR record: a second observer reads it from the bucket.
    observer = TPUTask(cloud, identifier, TaskSpec())
    server.attach(observer.client)
    assert "recover" in [event.code for event in observer.events()]


# -- EC2 + Auto Scaling Query over HTTP ----------------------------------------


@pytest.fixture()
def aws_task(monkeypatch):
    from tpu_task.backends.aws.task import AWSRealTask
    from tpu_task.common.cloud import AWSCredentials, Cloud, Credentials, Provider
    from tpu_task.storage.object_store_emulators import LoopbackS3

    cloud = Cloud(provider=Provider.AWS, region="us-east-1",
                  credentials=Credentials(aws=AWSCredentials(
                      access_key_id="AKIDEXAMPLE",
                      secret_access_key="secret")))
    spec = TaskSpec(size=Size(machine="m", storage=64),
                    environment=Environment(script="#!/bin/sh\necho hi\n"),
                    parallelism=2, spot=SPOT_ENABLED)
    with LoopbackAws() as control, LoopbackS3() as s3:
        task = AWSRealTask(cloud, Identifier.deterministic("loopback-aws"),
                           spec)
        control.attach(task.ec2)
        control.attach(task.asg_client)
        s3.attach(task.bucket.backend)
        for query_client in (task.ec2, task.asg_client):
            query_client._sleep = FakeSleep()
        # Backends re-opened from connection strings (status folding, wheel
        # staging, delete_storage) reuse the attached loopback S3 backend —
        # still real HTTP, same server.
        import importlib

        from tpu_task.storage import backends as backends_mod

        sync_mod = importlib.import_module("tpu_task.storage.sync")
        from tpu_task.storage import Connection

        def loop_open(remote):
            conn = (Connection.parse(remote) if remote.startswith(":")
                    else Connection(backend="local", container="",
                                    path=remote))
            return task.bucket.backend, conn

        for module in (sync_mod, backends_mod):
            monkeypatch.setattr(module, "open_backend", loop_open)
        yield control, s3, task


@pytest.mark.slow
def test_aws_full_lifecycle_over_http(aws_task):
    """The real AWSRealTask composition end-to-end against the stateful
    loopback control plane: create → read → stop → delete."""
    control, s3, task = aws_task

    task.create()
    task.create()  # full idempotency: every duplicate maps to no-op
    name = task.identifier.long()
    assert name in control.launch_templates
    assert name in control.asgs
    assert control.asgs[name]["desired"] == 2  # Start = parallelism
    template = control.launch_templates[name]
    assert template["LaunchTemplateData.ImageId"] == "ami-newest"
    assert template["LaunchTemplateData.BlockDeviceMapping.1.Ebs."
                    "VolumeSize"] == "64"
    recorded = template["LaunchTemplateData.TagSpecification.1.Tag.1.Value"]
    assert recorded.startswith(":s3,") and "secret" not in recorded
    spot = control.asgs[name]["params"]
    assert spot["MixedInstancesPolicy.InstancesDistribution."
                "OnDemandPercentageAboveBaseCapacity"] == "0"

    task.read()
    assert task.spec.status.get(StatusCode.ACTIVE) == 2
    assert len(task.get_addresses()) == 2
    assert any(event.code == "Successful" for event in task.spec.events)
    assert task.observed_parallelism() == 2

    task.stop()
    task.read()
    assert task.spec.status.get(StatusCode.ACTIVE, 0) == 0

    task.delete()
    task.delete()  # idempotent: every NotFound tolerated
    assert name not in control.asgs
    assert name not in control.launch_templates
    assert name not in control.key_pairs
    assert name not in control.security_groups
    assert all(a.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
               for a in control.auth_headers)


def test_aws_bare_read_recovers_remote_over_http(aws_task):
    """A fresh task (empty spec) resolves its storage from the launch
    template's tags through the real wire path."""
    from tpu_task.backends.aws.task import AWSRealTask

    control, s3, task = aws_task
    task.create()

    fresh = AWSRealTask(task.cloud, task.identifier, TaskSpec())
    control.attach(fresh.ec2)
    remote = fresh._remote()
    assert remote.startswith(":s3,")
    assert "access_key_id='AKIDEXAMPLE'" in remote  # re-injected locally
    assert remote.endswith(f":{task.identifier.long()}")


# -- ARM (Azure) over HTTP -----------------------------------------------------


def _az_cloud():
    from tpu_task.common.cloud import AZCredentials, Cloud, Credentials, Provider

    return Cloud(provider=Provider.AZ, region="us-east",
                 credentials=Credentials(az=AZCredentials(
                     client_id="cid", client_secret="csecret",
                     subscription_id="sub-1", tenant_id="tenant-1")))


@pytest.fixture()
def az_task(monkeypatch):
    from tpu_task.backends.az import resources as az_resources
    from tpu_task.backends.az.emulator import LoopbackArm
    from tpu_task.backends.az.task import AZRealTask
    from tpu_task.storage.object_store_emulators import LoopbackAzureBlob

    spec = TaskSpec(size=Size(machine="m", storage=64),
                    environment=Environment(script="#!/bin/sh\necho hi\n"),
                    parallelism=2, spot=SPOT_ENABLED)
    with LoopbackArm() as control, LoopbackAzureBlob() as blob:
        task = AZRealTask(_az_cloud(), Identifier.deterministic("loopback-az"),
                          spec)
        control.attach(task.client)
        task.client._sleep = FakeSleep()

        # Every BlobContainer (they are built per call) gets its data-plane
        # backend pointed at the blob loopback — still real HTTP.
        original_container = az_resources.BlobContainer

        class AttachedContainer(original_container):
            def __init__(self, account, key, name):
                super().__init__(account, key, name)
                blob.attach(self.backend)

        monkeypatch.setattr(az_resources, "BlobContainer", AttachedContainer)

        shared = AttachedContainer(task.identifier.short(), "a2V5",
                                   task.identifier.long()).backend

        import importlib

        from tpu_task.storage import Connection
        from tpu_task.storage import backends as backends_mod

        sync_mod = importlib.import_module("tpu_task.storage.sync")

        def loop_open(remote):
            conn = (Connection.parse(remote) if remote.startswith(":")
                    else Connection(backend="local", container="", path=remote))
            return shared, conn

        for module in (sync_mod, backends_mod):
            monkeypatch.setattr(module, "open_backend", loop_open)
        yield control, blob, task


@pytest.mark.slow
def test_az_full_lifecycle_over_http(az_task):
    """The real AZRealTask composition end-to-end against the stateful ARM
    loopback: create → read → stop → delete, resource-group containment."""
    control, blob, task = az_task
    name = task.identifier.long()

    task.create()
    task.create()  # idempotent: ARM PUT upserts, container 409 tolerated
    group = control.groups[name]
    assert f"Microsoft.Storage/storageAccounts/{task.identifier.short()}" \
        in group
    assert f"Microsoft.Network/networkSecurityGroups/{name}" in group
    assert f"Microsoft.Network/virtualNetworks/{name}" in group
    vmss = group[f"Microsoft.Compute/virtualMachineScaleSets/{name}"]
    assert vmss["sku"]["capacity"] == 2  # Start = parallelism via PATCH
    profile = vmss["properties"]["virtualMachineProfile"]
    assert profile["priority"] == "Spot"
    assert profile["billingProfile"]["maxPrice"] == -1  # spot 0 → no cap
    assert profile["osProfile"]["customData"]  # bootstrap rendered
    assert vmss["tags"]["tpu-task-remote"].startswith(":azureblob")
    assert "key" not in vmss["tags"]["tpu-task-remote"]

    task.read()
    assert task.spec.status.get(StatusCode.ACTIVE) == 2
    assert task.get_addresses() == ["20.0.0.4", "20.0.0.5"]
    assert any(event.code == "ProvisioningState/succeeded"
               for event in task.spec.events)
    assert task.observed_parallelism() == 2

    task.stop()
    task.read()
    assert task.spec.status.get(StatusCode.ACTIVE, 0) == 0

    task.delete()
    task.delete()  # idempotent: RG 404 tolerated
    assert name not in control.groups
    assert all(a.startswith("Bearer ") for a in control.auth_headers)


def test_az_multinet_nsg_rule_passes_arm_validation(az_task):
    """A multi-net firewall rule must emit AddressPrefixes ONLY — the
    emulator rejects the singular+plural combination exactly like live ARM
    (ADVICE r3 regression guard)."""
    from tpu_task.backends.az.resources import SecurityGroup
    from tpu_task.common.values import Firewall, FirewallRule

    control, blob, task = az_task
    task.resource_group.create()
    firewall = Firewall(
        ingress=FirewallRule(ports=[22], nets=["1.2.3.0/24", "5.6.7.0/24"]),
        egress=FirewallRule(ports=None, nets=["10.0.0.0/8", "11.0.0.0/8"]))
    nsg = SecurityGroup(task.client, task.identifier.long(), "multi",
                        task.region, firewall)
    nsg.create()  # live-ARM shape check: 400 would raise HTTPError
    stored = control.groups[task.identifier.long()][
        "Microsoft.Network/networkSecurityGroups/multi"]
    rules = {rule["name"]: rule["properties"]
             for rule in stored["properties"]["securityRules"]}
    assert rules["multi-in-22"]["sourceAddressPrefixes"] == \
        ["1.2.3.0/24", "5.6.7.0/24"]
    assert "sourceAddressPrefix" not in rules["multi-in-22"]
    # ports=None egress with nets: any-port Allow precedes the deny-all.
    assert rules["multi-out-any"]["destinationPortRange"] == "*"
    assert rules["multi-out-deny"]["access"] == "Deny"


def test_az_bare_read_recovers_remote_over_http(az_task):
    """A fresh task (empty spec) resolves its storage from the VMSS tag and
    re-fetches the account key via listKeys — nothing secret in the tag."""
    from tpu_task.backends.az.emulator import FIXED_ACCOUNT_KEY
    from tpu_task.backends.az.task import AZRealTask

    control, blob, task = az_task
    task.create()

    fresh = AZRealTask(task.cloud, task.identifier, TaskSpec())
    control.attach(fresh.client)
    fresh.client._sleep = FakeSleep()
    remote = fresh._remote()
    assert remote.startswith(":azureblob")
    assert f"key='{FIXED_ACCOUNT_KEY}'" in remote  # re-fetched, not recorded


# -- GCE compute over HTTP -----------------------------------------------------


@pytest.fixture()
def gce_task(monkeypatch):
    import json as _json

    from tpu_task.backends.gcp.emulator import LoopbackCompute
    from tpu_task.backends.gcp.task import GCERealTask
    from tpu_task.common.cloud import Cloud, Credentials, GCPCredentials, Provider
    from tpu_task.storage.gcs_emulator import LoopbackGCS

    cloud = Cloud(provider=Provider.GCP, region="us-west1-b",
                  credentials=Credentials(gcp=GCPCredentials(
                      application_credentials=_json.dumps(
                          {"project_id": "proj", "client_email": "sa@proj",
                           "private_key": "unused"}))))
    spec = TaskSpec(size=Size(machine="m", storage=64),
                    environment=Environment(script="#!/bin/sh\necho hi\n"),
                    parallelism=2, spot=SPOT_ENABLED)
    with LoopbackCompute() as control, LoopbackGCS() as gcs:
        task = GCERealTask(cloud, Identifier.deterministic("loopback-gce"),
                           spec)
        control.attach(task.client)
        task.client._sleep = FakeSleep()
        gcs.attach(task.bucket.backend)

        import importlib

        from tpu_task.storage import Connection
        from tpu_task.storage import backends as backends_mod

        sync_mod = importlib.import_module("tpu_task.storage.sync")

        def loop_open(remote):
            conn = (Connection.parse(remote) if remote.startswith(":")
                    else Connection(backend="local", container="", path=remote))
            return task.bucket.backend, conn

        for module in (sync_mod, backends_mod):
            monkeypatch.setattr(module, "open_backend", loop_open)
        yield control, gcs, task


@pytest.mark.slow
def test_gce_full_lifecycle_over_http(gce_task):
    """The real GCERealTask composition end-to-end against the stateful
    compute loopback: create → read → stop → delete, with the 6-rule
    firewall scheme and operation polling on real sockets."""
    control, gcs, task = gce_task
    name = task.identifier.long()

    task.create()
    assert name in gcs.buckets
    assert len(control.firewalls) == 6
    assert sorted(control.firewalls) == sorted(
        f"{name}-{suffix}" for suffix in ("e1", "i1", "e2", "i2", "e3", "i3"))
    template = control.templates[name]
    disks = template["properties"]["disks"]
    assert disks[0]["initializeParams"]["diskSizeGb"] == 64
    metadata = {item["key"]: item["value"]
                for item in template["properties"]["metadata"]["items"]}
    assert metadata["startup-script"].startswith("#!/")
    assert metadata["tpu-task-remote"].startswith(":googlecloudstorage")
    assert "private_key" not in metadata["tpu-task-remote"]  # sanitized
    assert control.migs[name]["target_size"] == 2  # Start = parallelism

    task.read()
    assert task.spec.status.get(StatusCode.ACTIVE) == 2
    assert len(task.get_addresses()) == 2
    assert task.observed_parallelism() == 2

    control.fail(name, "QUOTA_EXCEEDED", "zone exhausted")
    task.spec.status = {}
    task.read()
    assert any(event.code == "QUOTA_EXCEEDED" for event in task.spec.events)

    task.stop()
    assert control.migs[name]["target_size"] == 0

    task.delete()
    task.delete()  # idempotent: 404s tolerated throughout
    assert name not in control.migs
    assert name not in control.templates
    assert not control.firewalls
    assert name not in gcs.buckets
    assert all(a.startswith("Bearer ") for a in control.auth_headers)


def test_gce_image_family_fallback_over_http(gce_task):
    """Direct image 404 → family endpoint, through the real retry stack."""
    from tpu_task.backends.gcp.resources import Image

    control, gcs, task = gce_task
    image = Image(task.client, "me@my-proj/my-family")
    image.read()
    assert image.ssh_user == "me"
    assert image.resource["selfLink"] == "family-link/my-proj/my-family"


@pytest.mark.slow
def test_gce_bare_read_recovers_remote_over_http(gce_task):
    """A fresh task (empty spec) resolves its storage from the template
    metadata through the real wire path, re-injecting local credentials."""
    from tpu_task.backends.gcp.task import GCERealTask

    control, gcs, task = gce_task
    task.create()

    fresh = GCERealTask(task.cloud, task.identifier, TaskSpec())
    control.attach(fresh.client)
    fresh.client._sleep = FakeSleep()
    remote = fresh._remote()
    assert remote.startswith(":googlecloudstorage")
    assert "service_account_credentials" in remote  # re-injected locally
    assert remote.endswith(f":{task.identifier.long()}")


# -- CLI end-to-end over the loopback control plane ----------------------------


def test_cli_lifecycle_over_loopback_tpu(tmp_path, monkeypatch, capsys):
    """The closest real-cloud rehearsal this environment permits: drive
    `create → read --follow → delete` through cli/main.py AS A USER WOULD —
    flag bridge → TaskSpec → TPUTask → RestTpuClient → real HTTP against
    LoopbackTpu → bucket mailbox → status folding → follow exit code. The
    worker's side (logs, status JSON, self-destruct `stop`) is simulated
    exactly as machine-script semantics define it (tpl:51 status report,
    tpl:14 self-stop). Data plane: local-directory bucket root (the role
    rclone's local backend plays in the reference's tests)."""
    from tpu_task.backends.tpu import api as tpu_api
    from tpu_task.cli.main import main as cli_main

    bucket_root = tmp_path / "buckets"
    bucket_root.mkdir()
    workdir = tmp_path / "work"
    workdir.mkdir()
    (workdir / "input.txt").write_text("payload")
    monkeypatch.setenv("TPU_TASK_LOCAL_BUCKET_ROOT", str(bucket_root))
    monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS_DATA",
                       json.dumps({"project_id": "proj"}))
    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    # Wheel staging is covered by its own tests; a cold `pip wheel` build
    # here would only slow the lifecycle under test.
    monkeypatch.setattr("tpu_task.machine.wheel.ensure_wheel", lambda: None)
    monkeypatch.setattr("time.sleep", lambda _s: None)  # LRO + follow pacing

    with LoopbackTpu() as server:
        original_init = tpu_api.RestTpuClient.__init__

        def attached_init(self, *args, **kwargs):
            original_init(self, *args, **kwargs)
            server.attach(self)

        monkeypatch.setattr(tpu_api.RestTpuClient, "__init__", attached_init)

        # -- create -----------------------------------------------------------
        create_args = [
            "--cloud", "tpu", "--region", "us-central2",
            "create", "--name", "cli-e2e", "--machine", "v4-8",
            "--workdir", str(workdir), "--output", "results",
            "--script", "#!/bin/bash\necho hello-from-worker\n",
        ]
        rc = cli_main(create_args)
        assert rc == 0
        identifier = capsys.readouterr().out.strip().splitlines()[-1]
        assert identifier.startswith("tpi-cli-e2e-")
        # Reference smoke-test discipline: every operation runs twice
        # (task_smoke_test.go:180-181). A bare name salts a fresh random
        # identifier, so true idempotency is re-creating by the FULL
        # identifier: same task, create tolerates the existing resources.
        recreate_args = list(create_args)
        recreate_args[recreate_args.index("cli-e2e")] = identifier
        assert cli_main(recreate_args) == 0
        assert capsys.readouterr().out.strip().splitlines()[-1] == identifier
        assert len([name for name in server.qrs
                    if name.startswith("tpi-cli-e2e-")]) == 1

        qr_name = f"{identifier}-0"
        assert server.qrs[qr_name]["state"] == "ACTIVE"
        bucket = bucket_root / identifier
        assert (bucket / "data" / "input.txt").read_text() == "payload"

        # -- the worker's side, per machine-script semantics ------------------
        reports = bucket / "reports"
        reports.mkdir(exist_ok=True)
        (reports / "task-w0").write_text(
            "2026-07-30T12:00:00+00:00 hello-from-worker\n")
        (reports / "status-w0").write_text(
            '{"result": "exit-code", "code": "0", "status": "0"}')
        (bucket / "data" / "results").mkdir()
        (bucket / "data" / "results" / "out.txt").write_text("answer")
        # ExecStopPost self-destruct: the worker calls `stop` on itself.
        rc = cli_main(["--cloud", "tpu", "--region", "us-central2",
                       "stop", identifier])
        assert rc == 0
        assert qr_name not in server.qrs

        # -- read --follow: logs stream, terminal status maps to exit 0 -------
        rc = cli_main(["--cloud", "tpu", "--region", "us-central2",
                       "read", "--follow", identifier])
        assert rc == 0
        assert "hello-from-worker" in capsys.readouterr().out

        # -- delete: outputs pulled, bucket emptied ---------------------------
        rc = cli_main(["--cloud", "tpu", "--region", "us-central2",
                       "delete", "--workdir", str(workdir),
                       "--output", "results", identifier])
        assert rc == 0
        # Double delete tolerated (same smoke discipline).
        assert cli_main(["--cloud", "tpu", "--region", "us-central2",
                         "delete", identifier]) == 0
        assert (workdir / "results" / "out.txt").read_text() == "answer"
        assert list(bucket.rglob("*")) in ([], [bucket / "data"]) or \
            not any(p.is_file() for p in bucket.rglob("*"))


def test_cli_follow_exit_1_on_failure_over_loopback(tmp_path, monkeypatch,
                                                    capsys):
    """A worker reporting a nonzero exit folds to `failed` and read --follow
    exits 1 — the reference's read.go:105-124 exit-code contract."""
    from tpu_task.backends.tpu import api as tpu_api
    from tpu_task.cli.main import main as cli_main

    bucket_root = tmp_path / "buckets"
    bucket_root.mkdir()
    monkeypatch.setenv("TPU_TASK_LOCAL_BUCKET_ROOT", str(bucket_root))
    monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS_DATA",
                       json.dumps({"project_id": "proj"}))
    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    monkeypatch.setattr("tpu_task.machine.wheel.ensure_wheel", lambda: None)
    monkeypatch.setattr("time.sleep", lambda _s: None)

    with LoopbackTpu() as server:
        original_init = tpu_api.RestTpuClient.__init__

        def attached_init(self, *args, **kwargs):
            original_init(self, *args, **kwargs)
            server.attach(self)

        monkeypatch.setattr(tpu_api.RestTpuClient, "__init__", attached_init)

        rc = cli_main(["--cloud", "tpu", "--region", "us-central2",
                       "create", "--name", "cli-fail", "--machine", "v4-8",
                       "--workdir", "", "--script", "#!/bin/bash\nexit 3\n"])
        assert rc == 0
        identifier = capsys.readouterr().out.strip().splitlines()[-1]

        bucket = bucket_root / identifier
        (bucket / "reports").mkdir(parents=True, exist_ok=True)
        (bucket / "reports" / "status-w0").write_text(
            '{"result": "exit-code", "code": "3", "status": "3"}')
        cli_main(["--cloud", "tpu", "--region", "us-central2",
                  "stop", identifier])

        rc = cli_main(["--cloud", "tpu", "--region", "us-central2",
                       "read", "--follow", identifier])
        assert rc == 1
        cli_main(["--cloud", "tpu", "--region", "us-central2",
                  "delete", identifier])
