"""TPU REST and EC2/ASG Query control planes end-to-end over real HTTP.

The unit suites (test_tpu_backend.py, test_aws_real.py) verify behavior
against injected in-process transports; these tests close VERDICT r3 weak
spot #1 by running the SAME lifecycles through real sockets — Bearer/SigV4
auth headers, the retry layer, JSON/XML parsing, and LRO operation polling
all execute against stateful loopback servers
(backends/tpu/emulator.py, backends/aws/emulator.py).
"""

import json

import pytest

from test_http_resilience import FakeSleep

from tpu_task.backends.aws.emulator import LoopbackAws
from tpu_task.backends.tpu.emulator import LoopbackTpu
from tpu_task.common.errors import ResourceNotFoundError
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    Environment,
    Size,
    SPOT_ENABLED,
    StatusCode,
    Task as TaskSpec,
)


# -- TPU REST over HTTP --------------------------------------------------------


@pytest.fixture()
def tpu_client():
    from tpu_task.backends.tpu.api import RestTpuClient

    with LoopbackTpu() as server:
        client = RestTpuClient(project="proj", zone="us-central2-b")
        server.attach(client)
        yield server, client


def _qr_spec(**overrides):
    from tpu_task.backends.tpu.api import QueuedResourceSpec

    base = dict(
        node_id="node-0", accelerator_type="v4-16",
        runtime_version="tpu-ubuntu2204-base",
        startup_script="#!/bin/bash\necho boot\n",
        metadata={"tpu-task-remote": ":googlecloudstorage:bkt/task"},
        labels={"tpu-task": "1"}, spot=True,
    )
    base.update(overrides)
    return QueuedResourceSpec(**base)


def test_tpu_lifecycle_over_http(tpu_client, monkeypatch):
    """create (LRO polled) → get (full spec echo) → list → node → delete →
    404, with Bearer auth on every request."""
    server, client = tpu_client
    monkeypatch.setattr("time.sleep", lambda _s: None)  # LRO waiter pacing

    client.create_queued_resource("qr-0", _qr_spec())
    client.create_queued_resource("qr-0", _qr_spec())  # idempotent: 409 → ok

    info = client.get_queued_resource("qr-0")
    assert info.state == "ACTIVE"
    # The GET echoes the FULL created spec — what bare-read recovery needs.
    assert info.spec.startup_script == "#!/bin/bash\necho boot\n"
    assert info.spec.metadata["tpu-task-remote"] == \
        ":googlecloudstorage:bkt/task"
    assert info.spec.spot is True
    assert info.spec.accelerator_type == "v4-16"

    assert client.list_queued_resources() == ["qr-0"]
    node = client.get_node("node-0")
    assert node.state == "READY"
    assert node.worker_count == 2  # v4-16 → 2 hosts
    assert len(node.endpoints) == 2

    client.delete_queued_resource("qr-0")
    with pytest.raises(ResourceNotFoundError):
        client.get_queued_resource("qr-0")
    assert all(a.startswith("Bearer ") for a in server.auth_headers)


def test_tpu_preemption_recovery_over_http(tpu_client, tmp_path, monkeypatch):
    """The flagship reconciler over real sockets: a bare-read TPUTask sees
    SUSPENDED, re-queues from the spec echoed by the API, and persists the
    durable recovery event — no injected transports anywhere."""
    from tpu_task.backends.tpu.task import TPUTask
    from tpu_task.common.cloud import Cloud, Credentials, GCPCredentials, Provider

    server, client = tpu_client
    monkeypatch.setattr("time.sleep", lambda _s: None)
    bucket = tmp_path / "bucket"
    bucket.mkdir()

    identifier = Identifier.deterministic("loopback-recover")
    name = f"{identifier.long()}-0"
    client.create_queued_resource(name, _qr_spec(
        node_id=name, metadata={"tpu-task-remote": str(bucket)}))
    server.preempt(name)

    cloud = Cloud(provider=Provider.TPU, region="us-central2-b",
                  credentials=Credentials(gcp=GCPCredentials(
                      application_credentials=json.dumps(
                          {"project_id": "proj"}))))
    task = TPUTask(cloud, identifier, TaskSpec())  # bare read: empty spec
    server.attach(task.client)

    task.read()
    assert server.qrs[name]["state"] == "ACTIVE"  # re-queued
    requeued = task.client.get_queued_resource(name)
    assert requeued.spec.startup_script == "#!/bin/bash\necho boot\n"
    assert requeued.spec.spot is True

    # Durable MTTR record: a second observer reads it from the bucket.
    observer = TPUTask(cloud, identifier, TaskSpec())
    server.attach(observer.client)
    assert "recover" in [event.code for event in observer.events()]


# -- EC2 + Auto Scaling Query over HTTP ----------------------------------------


@pytest.fixture()
def aws_task(monkeypatch):
    from tpu_task.backends.aws.task import AWSRealTask
    from tpu_task.common.cloud import AWSCredentials, Cloud, Credentials, Provider
    from tpu_task.storage.object_store_emulators import LoopbackS3

    cloud = Cloud(provider=Provider.AWS, region="us-east-1",
                  credentials=Credentials(aws=AWSCredentials(
                      access_key_id="AKIDEXAMPLE",
                      secret_access_key="secret")))
    spec = TaskSpec(size=Size(machine="m", storage=64),
                    environment=Environment(script="#!/bin/sh\necho hi\n"),
                    parallelism=2, spot=SPOT_ENABLED)
    with LoopbackAws() as control, LoopbackS3() as s3:
        task = AWSRealTask(cloud, Identifier.deterministic("loopback-aws"),
                           spec)
        control.attach(task.ec2)
        control.attach(task.asg_client)
        s3.attach(task.bucket.backend)
        for query_client in (task.ec2, task.asg_client):
            query_client._sleep = FakeSleep()
        # Backends re-opened from connection strings (status folding, wheel
        # staging, delete_storage) reuse the attached loopback S3 backend —
        # still real HTTP, same server.
        import importlib

        from tpu_task.storage import backends as backends_mod

        sync_mod = importlib.import_module("tpu_task.storage.sync")
        from tpu_task.storage import Connection

        def loop_open(remote):
            conn = (Connection.parse(remote) if remote.startswith(":")
                    else Connection(backend="local", container="",
                                    path=remote))
            return task.bucket.backend, conn

        for module in (sync_mod, backends_mod):
            monkeypatch.setattr(module, "open_backend", loop_open)
        yield control, s3, task


def test_aws_full_lifecycle_over_http(aws_task):
    """The real AWSRealTask composition end-to-end against the stateful
    loopback control plane: create → read → stop → delete."""
    control, s3, task = aws_task

    task.create()
    task.create()  # full idempotency: every duplicate maps to no-op
    name = task.identifier.long()
    assert name in control.launch_templates
    assert name in control.asgs
    assert control.asgs[name]["desired"] == 2  # Start = parallelism
    template = control.launch_templates[name]
    assert template["LaunchTemplateData.ImageId"] == "ami-newest"
    assert template["LaunchTemplateData.BlockDeviceMapping.1.Ebs."
                    "VolumeSize"] == "64"
    recorded = template["LaunchTemplateData.TagSpecification.1.Tag.1.Value"]
    assert recorded.startswith(":s3,") and "secret" not in recorded
    spot = control.asgs[name]["params"]
    assert spot["MixedInstancesPolicy.InstancesDistribution."
                "OnDemandPercentageAboveBaseCapacity"] == "0"

    task.read()
    assert task.spec.status.get(StatusCode.ACTIVE) == 2
    assert len(task.get_addresses()) == 2
    assert any(event.code == "Successful" for event in task.spec.events)
    assert task.observed_parallelism() == 2

    task.stop()
    task.read()
    assert task.spec.status.get(StatusCode.ACTIVE, 0) == 0

    task.delete()
    task.delete()  # idempotent: every NotFound tolerated
    assert name not in control.asgs
    assert name not in control.launch_templates
    assert name not in control.key_pairs
    assert name not in control.security_groups
    assert all(a.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
               for a in control.auth_headers)


def test_aws_bare_read_recovers_remote_over_http(aws_task):
    """A fresh task (empty spec) resolves its storage from the launch
    template's tags through the real wire path."""
    from tpu_task.backends.aws.task import AWSRealTask

    control, s3, task = aws_task
    task.create()

    fresh = AWSRealTask(task.cloud, task.identifier, TaskSpec())
    control.attach(fresh.ec2)
    remote = fresh._remote()
    assert remote.startswith(":s3,")
    assert "access_key_id='AKIDEXAMPLE'" in remote  # re-injected locally
    assert remote.endswith(f":{task.identifier.long()}")
