"""End-to-end GCS data-plane integration over a real HTTP socket.

Drives the actual GCSBackend — resumable chunked uploads, parallel ranged
downloads, list/delete — against the in-process loopback emulator, so the
full protocol path (urllib, thread pools, Content-Range bookkeeping) is
exercised without scripted fakes. Role in the reference: the rclone `local`
backend integration tests (storage_test.go:54-107), upgraded to keep HTTP in
the loop.
"""

import os

import pytest

from tpu_task.storage.backends import GCSBackend
from tpu_task.storage.gcs_emulator import LoopbackGCS


@pytest.fixture()
def loopback():
    with LoopbackGCS() as server:
        yield server


def _backend(server, prefix=""):
    backend = GCSBackend("bkt", prefix)
    server.attach(backend)
    return backend


def test_small_object_roundtrip(loopback):
    backend = _backend(loopback)
    backend.write("reports/status-1", b'{"code": "0"}')
    assert backend.read("reports/status-1") == b'{"code": "0"}'
    assert backend.list("reports") == ["reports/status-1"]
    backend.delete("reports/status-1")
    assert backend.list() == []


def test_prefix_is_scoped(loopback):
    backend = _backend(loopback, prefix="task-1")
    backend.write("data/file.txt", b"x")
    assert loopback.objects == {"task-1/data/file.txt": b"x"}
    assert backend.list() == ["data/file.txt"]


def test_large_object_streams_both_ways(loopback, tmp_path):
    """A multi-chunk checkpoint goes up via the resumable protocol and comes
    back via parallel ranged GETs, byte-identical."""
    backend = _backend(loopback)
    backend.UPLOAD_CHUNK = 256 * 1024
    backend.RESUMABLE_THRESHOLD = 256 * 1024
    backend.DOWNLOAD_CHUNK = 192 * 1024  # misaligned with upload chunk on purpose

    content = os.urandom(1024 * 1024 + 12345)
    source = tmp_path / "ckpt.bin"
    source.write_bytes(content)

    backend.write_from_file("checkpoints/step-100.bin", str(source))
    assert loopback.objects["checkpoints/step-100.bin"] == content

    restored = tmp_path / "restored.bin"
    backend.read_to_file("checkpoints/step-100.bin", str(restored))
    assert restored.read_bytes() == content


def test_large_bytes_write_uses_resumable(loopback):
    backend = _backend(loopback)
    backend.UPLOAD_CHUNK = 128 * 1024
    backend.RESUMABLE_THRESHOLD = 128 * 1024
    content = os.urandom(500 * 1024)
    backend.write("big.bin", content)
    assert loopback.objects["big.bin"] == content


def test_list_meta_sizes(loopback):
    backend = _backend(loopback)
    backend.write("a.txt", b"aaa")
    backend.write("b/c.txt", b"ccccc")
    meta = backend.list_meta()
    assert meta["a.txt"][0] == 3
    assert meta["b/c.txt"][0] == 5


def test_composite_upload_parallel_parts(loopback, tmp_path):
    """Above COMPOSE_THRESHOLD the object goes up as parallel part objects
    stitched by one compose call: byte-identical result, no part residue."""
    backend = _backend(loopback, prefix="task-9")
    backend.RESUMABLE_THRESHOLD = 64 * 1024
    backend.UPLOAD_CHUNK = 64 * 1024
    backend.COMPOSE_THRESHOLD = 256 * 1024
    backend.COMPOSE_PART = 128 * 1024

    content = os.urandom(1024 * 1024 + 999)  # 9 uneven parts
    source = tmp_path / "big.bin"
    source.write_bytes(content)

    backend.write_from_file("checkpoints/big.bin", str(source))
    assert loopback.objects["task-9/checkpoints/big.bin"] == content
    assert [k for k in loopback.objects if ".gcs-tmp/" in k] == []

    restored = tmp_path / "restored.bin"
    backend.read_to_file("checkpoints/big.bin", str(restored))
    assert restored.read_bytes() == content


def test_composite_upload_cleans_parts_on_failure(loopback, tmp_path):
    """A failed compose must not leak part objects (best-effort cleanup)."""
    backend = _backend(loopback)
    backend.RESUMABLE_THRESHOLD = 64 * 1024
    backend.UPLOAD_CHUNK = 64 * 1024
    backend.COMPOSE_THRESHOLD = 128 * 1024
    backend.COMPOSE_PART = 128 * 1024

    source = tmp_path / "big.bin"
    source.write_bytes(os.urandom(512 * 1024))

    original = backend._request

    def failing_request(method, url, **kwargs):
        if url.endswith("/compose"):
            raise RuntimeError("compose exploded")
        return original(method, url, **kwargs)

    backend._request = failing_request
    with pytest.raises(RuntimeError, match="compose exploded"):
        backend.write_from_file("checkpoints/big.bin", str(source))
    assert [k for k in loopback.objects if ".gcs-tmp/" in k] == []
    assert "checkpoints/big.bin" not in loopback.objects


def test_composite_parts_invisible_to_list_during_upload(loopback, tmp_path):
    """A list()/list_meta() issued WHILE parts exist must not surface them:
    the sync engine mirrors whatever list returns, and transient multi-MB
    part objects (or their mid-pull deletion) would corrupt a concurrent
    pull (advisor r4)."""
    backend = _backend(loopback)
    backend.RESUMABLE_THRESHOLD = 64 * 1024
    backend.UPLOAD_CHUNK = 64 * 1024
    backend.COMPOSE_THRESHOLD = 128 * 1024
    backend.COMPOSE_PART = 128 * 1024

    source = tmp_path / "big.bin"
    source.write_bytes(os.urandom(512 * 1024))

    observed = {}
    original = backend._request

    def snooping_request(method, url, **kwargs):
        if url.endswith("/compose"):
            # Parts are all uploaded at this instant; a concurrent reader
            # must not see them.
            observed["keys"] = backend.list()
            observed["meta"] = backend.list_meta()
        return original(method, url, **kwargs)

    backend._request = snooping_request
    backend.write_from_file("checkpoints/big.bin", str(source))
    assert [k for k in observed["keys"] if ".gcs-tmp/" in k] == []
    assert [k for k in observed["meta"] if ".gcs-tmp/" in k] == []
    # The parts genuinely existed at snoop time (raw store view).
    assert observed["keys"] is not None


def test_orphaned_composite_parts_purged_on_delete(loopback, monkeypatch):
    """A crash between part upload and the finally-block delete leaves
    .gcs-tmp/ orphans that list() hides; delete_storage must still purge
    them (via list_hidden) or bucket deletion would fail not-empty and the
    multi-MB orphans would leak invisibly forever (review r5)."""
    import importlib

    sync_module = importlib.import_module("tpu_task.storage.sync")

    backend = _backend(loopback, prefix="task-11")
    backend.write("real.txt", b"live")
    # Simulate the crash residue directly in the store.
    loopback.objects["task-11/.gcs-tmp/deadbeef/big.bin.part-00"] = b"x" * 128
    assert backend.list() == ["real.txt"]  # hidden from normal listing
    assert backend.list_hidden() == [".gcs-tmp/deadbeef/big.bin.part-00"]

    # Route delete_storage to the loopback-attached backend.
    monkeypatch.setattr(sync_module, "open_backend",
                        lambda remote: (backend, None))
    sync_module.delete_storage(":googlecloudstorage:bkt/task-11")
    assert [k for k in loopback.objects if k.startswith("task-11/")] == []
