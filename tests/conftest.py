"""Test configuration: force a virtual 8-device CPU platform for JAX tests.

Multi-chip TPU hardware is not available in CI; all sharding/parallelism tests
run on an 8-device virtual CPU mesh (same XLA SPMD partitioner as TPU).
Must run before any ``import jax`` anywhere in the test session.
"""

import os
import sys

# Force, not setdefault: the axon TPU tunnel exports JAX_PLATFORMS=axon,
# which would put the hermetic suite on one real chip instead of 8 CPU devices.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("TPU_TASK_TEST_REAL_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The axon sitecustomize imports jax at interpreter startup, baking the
    # env in before this file runs; update the live config too. jax itself
    # is optional — the orchestrator tests run without it.
    try:
        import jax
    except ImportError:
        pass
    else:
        jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Bucket-probe caches (shutdown marker, durable events, heartbeats) add
# observation latency that poll-based tests cannot afford; probe every read.
os.environ.setdefault("TPU_TASK_SHUTDOWN_PROBE_PERIOD", "0")
os.environ.setdefault("TPU_TASK_EVENTS_PROBE_PERIOD", "0")
os.environ.setdefault("TPU_TASK_HEARTBEAT_PROBE_PERIOD", "0")

import pytest  # noqa: E402

# Modules whose tests spawn real agent subprocesses with wall-clock sync
# loops: serialized below behind a CROSS-PROCESS flock. Two pytest
# processes running them concurrently starve each other until poll
# ceilings trip (r4: test_tpu_multihost_workers_all_run exceeded 180 s
# under a concurrent double-suite, passes alone in 5 s; r5: a CLI
# lifecycle test timed out the same way) — raising ceilings again would
# just move the cliff. One allowlist here, not a pasted shim per module.
AGENT_SUBPROCESS_MODULES = {
    "test_chaos",
    "test_chaos_soak",
    "test_cli",
    "test_frontend",
    "test_lifecycle_local",
    "test_scheduler",
    "test_tpu_backend",
}


# Tier-1 budget guard (ISSUE 16): the `-m 'not slow'` suite runs under a
# hard 870 s driver timeout with ~770–820 s of headroom actually spent —
# one new heavyweight test can tip it over. Any UNMARKED test that takes
# longer than this is flagged at session end so it gets a `slow` mark (or
# a diet) before the budget blows, without failing anyone's run.
TIER1_TEST_BUDGET_S = 30.0
_overbudget: list = []


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    import time as _time

    t0 = _time.perf_counter()
    yield
    wall = _time.perf_counter() - t0
    if wall > TIER1_TEST_BUDGET_S and item.get_closest_marker("slow") is None:
        _overbudget.append((item.nodeid, wall))


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if not _overbudget:
        return
    terminalreporter.section("tier-1 budget guard")
    for nodeid, wall in sorted(_overbudget, key=lambda x: -x[1]):
        terminalreporter.write_line(
            f"WARNING: {nodeid} took {wall:.1f}s (> {TIER1_TEST_BUDGET_S:.0f}s "
            f"budget) without a `slow` marker — mark it slow or shrink it")


@pytest.fixture(autouse=True, scope="module")
def agent_subprocess_serial(request):
    module = getattr(request.module, "__name__", "").rsplit(".", 1)[-1]
    if module not in AGENT_SUBPROCESS_MODULES:
        yield
        return
    import fcntl
    import tempfile

    path = os.path.join(tempfile.gettempdir(), "tpu-task-agent-tests.lock")
    handle = open(path, "a+")
    try:
        fcntl.flock(handle, fcntl.LOCK_EX)
        yield
    finally:
        try:
            fcntl.flock(handle, fcntl.LOCK_UN)
        finally:
            handle.close()
