"""Test configuration: force a virtual 8-device CPU platform for JAX tests.

Multi-chip TPU hardware is not available in CI; all sharding/parallelism tests
run on an 8-device virtual CPU mesh (same XLA SPMD partitioner as TPU).
Must run before any ``import jax`` anywhere in the test session.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
