"""The fully asynchronous engine loop (PR 16, ``ServingConfig.overlap``).

The contract (docs/parity.md "Async overlap"): overlap is a pure
SCHEDULING change — the host sweep of program N runs while the device
executes program N+1, admissions join the NEXT program, and several
admitting slots' chunks pack into one program (``prefill_slots``) — but
never a token: greedy and keyed sampled streams are bit-identical to the
synchronous loop at every ``micro_k``, preemption counts are equal (pool
pressure flushes to the synchronous edge before preempting, exactly
where the sync loop would), and ``obs=None`` stays zero-overhead.

Tier-1 pins the cheap core (batch-4 bit-identity at K ∈ {1, 8}, the
multi-slot burst, flush/export, attribution fields); the seeded
randomized-schedule soak across admit/retire/preempt interleavings is
``slow``.
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpu_task.ml.models import transformer
from tpu_task.ml.serving import ServingConfig, ServingEngine

TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32, n_kv_heads=2)

BASE = ServingConfig(slots=4, block_size=4, n_blocks=64, max_len=48,
                     chunk_tokens=4, prefix_cache=False)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _workload(seed=0, n=8, temps=False):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        prompt = rng.integers(0, TINY.vocab_size,
                              size=int(rng.integers(3, 12)))
        t = float(rng.choice([0.0, 0.8])) if temps else 0.0
        out.append(dict(prompt=prompt, max_new=int(rng.integers(3, 14)),
                        temperature=t, top_p=0.9 if t else None,
                        eos_token=7))
    return out

def _submit(engine, spec):
    return engine.submit(spec["prompt"], spec["max_new"],
                         temperature=spec["temperature"],
                         top_p=spec["top_p"], eos_token=spec["eos_token"])


def _drain(params, scfg, seed=0, n=8, temps=False, **engine_kw):
    engine = ServingEngine(params, TINY, scfg,
                           rng=jax.random.PRNGKey(99), **engine_kw)
    for spec in _workload(seed, n, temps):
        _submit(engine, spec)
    return engine.drain(), engine


def test_overlap_validation():
    with pytest.raises(ValueError, match="prefill_slots"):
        ServingConfig(prefill_slots=0)
    with pytest.raises(ValueError, match="prefill_slots"):
        ServingConfig(slots=4, prefill_slots=5)
    with pytest.raises(ValueError, match="overlap"):
        ServingConfig(overlap=True, prefill="bucketed",
                      prefix_cache=False)
    with pytest.raises(ValueError, match="overlap"):
        ServingConfig(overlap=True, spec_k=2)


@pytest.mark.perf
def test_overlap_greedy_streams_bit_identical(params):
    """The tier-1 pin of the tentpole: the overlapped loop's greedy
    streams at batch 4 — through chunked prefill, mixed eos/length
    retirement — are bit-identical to the synchronous loop's at
    micro_k 1 AND 8, with no extra preemptions and the overlap
    machinery demonstrably engaged (results lag one step, so the
    engine must have dispatched ahead)."""
    for k in (1, 8):
        scfg = dataclasses.replace(BASE, micro_k=k)
        ref, ref_eng = _drain(params, scfg)
        got, eng = _drain(params, dataclasses.replace(scfg, overlap=True))
        assert got == ref, f"greedy streams diverged at micro_k={k}"
        assert eng.preemption_count == ref_eng.preemption_count == 0
        assert eng.stats()["overlap"] is True
        assert eng.decode_steps > 0


def test_overlap_sampled_streams_identical(params):
    """Sampled streams ride position-keyed fold_in draws — schedule
    independent, so the overlapped loop must reproduce them exactly
    (unquantized; fp8/int8 replay is a documented tolerance class)."""
    ref, _ = _drain(params, BASE, temps=True)
    got, _ = _drain(params, dataclasses.replace(BASE, overlap=True),
                    temps=True)
    assert got == ref


def test_overlap_multi_slot_prefill_packs_burst(params):
    """prefill_slots > 1: an admission burst packs several admitting
    slots' chunks into ONE program — fewer chunk programs than a
    one-slot-per-step serialization, same streams."""
    scfg = dataclasses.replace(BASE, chunk_tokens=16)
    ref, ref_eng = _drain(params, scfg)
    for overlap in (False, True):
        packed = dataclasses.replace(scfg, prefill_slots=4,
                                     overlap=overlap)
        got, eng = _drain(params, packed)
        assert got == ref
        assert eng.chunk_steps < ref_eng.chunk_steps, \
            f"multi-slot prefill did not pack (overlap={overlap})"


def test_overlap_pool_pressure_flush_matches_sync_preemptions(params):
    """Pool pressure beyond eviction flushes the pipeline to the sync
    edge and preempts exactly where the synchronous loop would: equal
    preemption counts, identical streams, and the flush counter shows
    the fallback actually ran."""
    tight = dataclasses.replace(BASE, slots=3, n_blocks=10, max_len=32)
    ref, ref_eng = _drain(params, tight, seed=3, n=6)
    got, eng = _drain(params, dataclasses.replace(tight, overlap=True),
                      seed=3, n=6)
    assert got == ref
    assert eng.preemption_count == ref_eng.preemption_count > 0
    assert eng.overlap_flushes > 0


def test_overlap_export_inflight_flushes_and_resumes(params):
    """export_inflight() mid-pipeline flushes the in-flight program
    first (mirrors exact), and the export resumes into a fresh engine
    with streams identical to an uninterrupted synchronous run."""
    ref, _ = _drain(params, BASE, seed=5)
    engine = ServingEngine(params, TINY,
                           dataclasses.replace(BASE, overlap=True),
                           rng=jax.random.PRNGKey(99))
    rids = [_submit(engine, s) for s in _workload(5)]
    for _ in range(4):
        engine.step()
    exported = engine.export_inflight()
    assert engine._inflight is None        # the flush happened
    done = {rid: list(engine._requests[rid].tokens) for rid in rids
            if engine._requests[rid].status == "done"}
    resumed = ServingEngine(params, TINY,
                            dataclasses.replace(BASE, overlap=True),
                            rng=jax.random.PRNGKey(99))
    remap = resumed.resume_inflight(exported)
    out = resumed.drain()
    got = dict(done)
    for old, new in remap.items():
        got[old] = out[new]        # resumed streams carry their prefix
    assert got == ref


def test_overlap_goodput_attribution(params):
    """The overlap-aware 3-way split: with a program in flight across
    every mid-drain step, host work lands in overlapped_host_s, the
    residual host gap is ~zero, and busy_s still covers the step wall
    (the MFU denominator does not shrink)."""
    from tpu_task.obs import Obs

    engine = ServingEngine(params, TINY,
                           dataclasses.replace(BASE, overlap=True),
                           obs=Obs.create("async-goodput"))
    for spec in _workload(0):
        _submit(engine, spec)
    engine.drain()
    gp = engine.stats()["goodput"]
    assert gp["overlapped_host_s"] > 0
    assert gp["host_gap_frac"] < 0.1
    assert gp["in_program_frac"] + gp["host_gap_frac"] <= 1.0 + 1e-9


def test_overlap_obs_none_zero_overhead(params):
    """obs=None keeps the zero-overhead contract: no goodput meter, no
    step histograms — the overlapped loop never touches them."""
    engine = ServingEngine(params, TINY,
                           dataclasses.replace(BASE, overlap=True))
    for spec in _workload(0, n=3):
        _submit(engine, spec)
    engine.drain()
    assert engine._goodput is None


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_overlap_randomized_schedule_soak(params, seed):
    """Seeded randomized-schedule soak: arrivals interleaved with steps
    (admissions land mid-flight, retire under the pipeline), randomized
    prompt/max_new/eos/temperature mixes, pool sizes tight enough to
    preempt, micro_k and prefill_slots drawn per run — async streams
    and preemption counts must match the synchronous loop exactly."""
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(8, 14))
    specs = []
    for _ in range(n):
        prompt = rng.integers(0, TINY.vocab_size,
                              size=int(rng.integers(2, 14)))
        t = float(rng.choice([0.0, 0.7, 1.1]))
        specs.append(dict(prompt=prompt,
                          max_new=int(rng.integers(2, 16)),
                          temperature=t, top_p=0.9 if t else None,
                          eos_token=int(rng.integers(0, 16))))
    # steps to run between arrivals — the interleaving under test
    gaps = [int(rng.integers(0, 4)) for _ in specs]
    scfg = dataclasses.replace(
        BASE,
        slots=int(rng.integers(2, 5)),
        n_blocks=int(rng.integers(12, 40)),
        max_len=32,
        micro_k=int(rng.choice([1, 2, 8])),
        chunk_tokens=int(rng.choice([4, 16])))
    scfg = dataclasses.replace(
        scfg, prefill_slots=int(rng.integers(1, scfg.slots + 1)))

    def run(overlap):
        eng = ServingEngine(
            params, TINY, dataclasses.replace(scfg, overlap=overlap),
            rng=jax.random.PRNGKey(42))
        for spec, gap in zip(specs, gaps):
            _submit(eng, spec)
            for _ in range(gap):
                eng.step()
        return eng.drain(), eng

    ref, ref_eng = run(False)
    got, eng = run(True)
    assert got == ref
    assert eng.preemption_count == ref_eng.preemption_count
