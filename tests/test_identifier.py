"""Identifier properties, incl. the reference's hard-coded compatibility vectors
(reference: task/common/identifier_test.go:10-75) so IDs never drift."""

import secrets

import pytest

from tpu_task.common.identifier import (
    MAXIMUM_LONG_LENGTH,
    SHORT_LENGTH,
    Identifier,
    WrongIdentifierError,
    normalize,
)


def random_sentence(words=64):
    return " ".join(secrets.token_hex(4) for _ in range(words))


def test_stability():
    name = random_sentence()
    identifier = Identifier.deterministic(name)
    assert identifier.long() == Identifier.deterministic(name).long()
    assert identifier.short() == Identifier.deterministic(name).short()


def test_consistency():
    identifier = Identifier.deterministic("5299fe10-79e9-4c3b-b15e-036e8e60ab6c")
    parsed = Identifier.parse(identifier.long())
    assert parsed.long() == identifier.long()
    assert parsed.short() == identifier.short()


def test_homogeneity():
    identifier = Identifier.deterministic(random_sentence())
    long, short = identifier.long(), identifier.short()
    assert long.startswith("tpi-")
    assert all(c in "abcdefghijklmnopqrstuvwxyz0123456789-" for c in long)
    assert all(c in "abcdefghijklmnopqrstuvwxyz0123456789" for c in short)
    assert len(long) <= MAXIMUM_LONG_LENGTH
    assert len(short) == SHORT_LENGTH


def test_compatibility_vector():
    """Hard-coded vector from the reference test suite — must match exactly."""
    identifier = Identifier.deterministic("test")
    assert identifier.long() == "tpi-test-3z4xlzwq-3u0vweb4"
    assert identifier.short() == "3z4xlzwq3u0vweb4"
    parsed = Identifier.parse(identifier.long())
    assert parsed.long() == identifier.long()


def test_prefix():
    identifier = Identifier.deterministic("test", prefix="ipsum")
    assert identifier.long() == "ips-test-3z4xlzwq-3u0vweb4"
    assert identifier.short() == "3z4xlzwq3u0vweb4"
    assert Identifier.parse(identifier.long()).long() == identifier.long()


def test_randomness():
    first = Identifier.random("test")
    second = Identifier.random("test")
    assert first.long() != second.long()
    assert first.short() != second.short()
    assert "test" in first.long()


def test_random_petname():
    identifier = Identifier.random()
    assert identifier.name
    assert Identifier.parse(identifier.long()).long() == identifier.long()


def test_parse_rejects_garbage():
    with pytest.raises(WrongIdentifierError):
        Identifier.parse("not-a-valid-identifier")
    with pytest.raises(WrongIdentifierError):
        # Valid shape, wrong checksum.
        Identifier.parse("tpi-test-3z4xlzwq-00000000")


def test_normalize():
    assert normalize("Hello, World!") == "hello-world"
    assert normalize("--x--") == "x"
    assert len(normalize("a" * 100)) == 28


def test_validation_failures():
    """Names/prefixes that would produce unparseable identifiers fail loudly."""
    with pytest.raises(ValueError):
        Identifier.deterministic("!!!")
    with pytest.raises(ValueError):
        Identifier.deterministic("")
    with pytest.raises(ValueError):
        Identifier.deterministic("test", prefix="ab")
    with pytest.raises(WrongIdentifierError):
        Identifier.parse("tpi-test-3z4xlzwq-3u0vweb4\n")
