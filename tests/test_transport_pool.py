"""Pooled keep-alive HTTP transport: connection reuse, stale-socket
handling, bounded idle set, and the GCS batch-delete path.

The emulator-side connection counters make reuse falsifiable: N requests
over ≤ pool-size TCP connections (the pre-pool client opened one per
request). The pool's stale-socket single-retry and idle bounds are unit
tested through the injectable connection-factory seam, and ``send``'s
retry/``ok_statuses``/``with_headers`` contract is regression-tested
through the REAL pooled path against a scripted loopback server — the
fault-injection ``urlopen`` seam itself is covered by
test_http_resilience.py, which must keep passing unchanged.
"""

import http.client
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpu_task.storage.backends import GCSBackend, parallel_map
from tpu_task.storage.gcs_emulator import LoopbackGCS
from tpu_task.storage.http_util import HTTPPool, send


@pytest.fixture()
def loopback():
    with LoopbackGCS() as server:
        yield server


def _backend(server, prefix=""):
    backend = GCSBackend("bkt", prefix)
    server.attach(backend)
    return backend


# -- connection reuse over real sockets ---------------------------------------


def test_serial_requests_share_one_connection(loopback):
    backend = _backend(loopback)
    for i in range(25):
        backend.write(f"small/{i}", b"x")
    for i in range(25):
        assert backend.read(f"small/{i}") == b"x"
    for i in range(25):
        backend.delete(f"small/{i}")
    assert backend.list() == []
    # 76 requests from one client thread: the pooled transport must ride ONE
    # persistent connection; the per-request client opened 76.
    assert loopback.connections == 1


def test_concurrent_requests_bounded_by_pool(loopback):
    backend = _backend(loopback)
    parallel_map([lambda i=i: backend.write(f"obj/{i}", b"y")
                  for i in range(64)], 8)
    assert len(loopback.objects) == 64
    assert loopback.connections <= 8  # one per concurrent worker at most
    before = loopback.connections
    for i in range(64):
        assert backend.read(f"obj/{i}") == b"y"
    # The burst's connections were parked in the pool; the serial sweep
    # reuses them instead of opening more.
    assert loopback.connections == before


def test_concurrent_checkout_with_fault_injection(loopback):
    """Concurrent checkout under failures: workers racing the pool while the
    server 404s half the requests must neither wedge nor leak — every
    response (success or HTTPError) returns its connection for reuse."""
    backend = _backend(loopback)
    for i in range(0, 32, 2):
        backend.write(f"k/{i}", b"v")

    from tpu_task.common.errors import ResourceNotFoundError

    outcomes = []

    def fetch(i):
        try:
            backend.read(f"k/{i}")
            outcomes.append("hit")
        except ResourceNotFoundError:
            outcomes.append("miss")

    parallel_map([lambda i=i: fetch(i) for i in range(32)], 8)
    assert sorted(set(outcomes)) == ["hit", "miss"]
    assert outcomes.count("hit") == 16
    assert loopback.connections <= 8


def test_control_plane_polls_reuse_connection():
    from tpu_task.backends.tpu.api import RestTpuClient
    from tpu_task.backends.tpu.emulator import LoopbackTpu

    with LoopbackTpu() as plane:
        client = RestTpuClient("proj", "us-central2-b")
        plane.attach(client)
        for _ in range(10):
            client.list_nodes()
        assert plane.connections == 1


# -- pool unit behavior through the connection-factory seam -------------------


class _FakeRawResponse:
    def __init__(self, body, will_close):
        self.status, self.reason = 200, "OK"
        self.headers = {}
        self.will_close = will_close
        self._body = body

    def read(self):
        return self._body


class _FakeConn:
    """Scripted http.client connection double. Script entries:
    ("ok", body[, will_close]) | ("stale",)."""

    def __init__(self, script):
        self.script = list(script)
        self.closed = False
        self.sock = None
        self.timeout = None
        self._pending = None

    def request(self, method, path, body=None, headers=None):
        entry = self.script.pop(0)
        if entry[0] == "stale":
            raise http.client.RemoteDisconnected("server closed idle socket")
        self._pending = entry

    def getresponse(self):
        _kind, body, *rest = self._pending
        return _FakeRawResponse(body, rest[0] if rest else False)

    def close(self):
        self.closed = True


def _request(url="http://svc.example/a", method="GET"):
    return urllib.request.Request(url, method=method)


def test_stale_pooled_socket_retries_once_on_fresh_connection():
    made = []

    def connect(scheme, host, port, timeout):
        # First connection: one good response, then stale on reuse.
        script = ([("ok", b"first"), ("stale",)] if not made
                  else [("ok", b"second")])
        conn = _FakeConn(script)
        made.append(conn)
        return conn

    pool = HTTPPool(connect=connect)
    sleeps = []
    assert send("GET", "http://svc.example/a",
                urlopen=pool.urlopen, sleep=sleeps.append) == b"first"
    # Reused socket dies with zero bytes read → ONE fresh-connection retry
    # inside the pool, before (and without consuming) the backoff ladder.
    assert send("GET", "http://svc.example/b",
                urlopen=pool.urlopen, sleep=sleeps.append) == b"second"
    assert len(made) == 2
    assert pool.stale_retries == 1
    assert made[0].closed
    assert sleeps == []  # the backoff ladder never fired


def test_all_stale_parked_sockets_drain_without_consuming_backoff():
    """After a long pause the WHOLE idle set may be dead: one request must
    drain every stale socket and land on a fresh connection without burning
    any of send()'s backoff ladder."""
    made = []

    def connect(scheme, host, port, timeout):
        conn = _FakeConn([("ok", b"fresh")])
        made.append(conn)
        return conn

    pool = HTTPPool(connect=connect)
    key = ("http", "svc.example", 80)
    stale = [_FakeConn([("stale",)]) for _ in range(3)]
    for conn in stale:
        pool._checkin(key, conn)
    sleeps = []
    assert send("GET", "http://svc.example/a",
                urlopen=pool.urlopen, sleep=sleeps.append) == b"fresh"
    assert all(conn.closed for conn in stale)  # every dead socket discarded
    assert len(made) == 1                      # exactly one fresh connection
    assert pool.stale_retries == 3
    assert sleeps == []                        # backoff ladder untouched


def test_fresh_connection_failure_is_not_stale_retried():
    made = []

    def connect(scheme, host, port, timeout):
        conn = _FakeConn([("stale",)])
        made.append(conn)
        return conn

    pool = HTTPPool(connect=connect)
    with pytest.raises(urllib.error.URLError):
        pool.urlopen(_request())
    # A FRESH connection dying is a real transport error: surface it to the
    # caller's backoff ladder instead of looping inside the pool.
    assert len(made) == 1


def test_connection_close_response_is_not_pooled():
    made = []

    def connect(scheme, host, port, timeout):
        conn = _FakeConn([("ok", b"one", True)] if not made
                         else [("ok", b"two")])
        made.append(conn)
        return conn

    pool = HTTPPool(connect=connect)
    assert pool.urlopen(_request()).read() == b"one"
    assert made[0].closed  # server said Connection: close (will_close)
    assert pool.urlopen(_request()).read() == b"two"
    assert len(made) == 2


def test_idle_set_is_bounded():
    pool = HTTPPool(max_idle_per_host=2)
    key = ("http", "svc.example", 80)
    conns = [_FakeConn([]) for _ in range(3)]
    for conn in conns:
        pool._checkin(key, conn)
    assert [conn.closed for conn in conns] == [False, False, True]
    pool.purge()
    assert all(conn.closed for conn in conns)


# -- send() contract through the REAL pooled path -----------------------------


class _ScriptedHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True

    def _serve(self):
        length = int(self.headers.get("Content-Length", "0"))
        if length:
            self.rfile.read(length)
        code, headers, body = self.server.script.pop(0)
        self.send_response(code)
        for name, value in headers.items():
            self.send_header(name, value)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    do_GET = do_PUT = do_POST = _serve

    def log_message(self, *args):
        pass


@pytest.fixture()
def scripted_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _ScriptedHandler)
    server.script = []
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield server
    finally:
        server.shutdown()
        server.server_close()
        from tpu_task.storage.http_util import default_pool

        default_pool().purge(port=server.server_address[1])


def test_send_default_transport_honors_retry_after_ok_statuses_with_headers(
        scripted_server):
    """Regression: the full send() contract — Retry-After pacing,
    ok_statuses error-as-success, with_headers — through the DEFAULT pooled
    transport over a real socket, not an injected fake."""
    scripted_server.script[:] = [
        (429, {"Retry-After": "2"}, b""),
        (308, {"Range": "bytes=0-41"}, b"partial"),
    ]
    port = scripted_server.server_address[1]
    sleeps = []
    body, headers = send(
        "PUT", f"http://127.0.0.1:{port}/chunk", data=b"x",
        ok_statuses=(308,), with_headers=True, sleep=sleeps.append)
    assert body == b"partial"
    assert {k.lower(): v for k, v in headers.items()}["range"] == "bytes=0-41"
    assert sleeps == [2.0]


def test_send_default_transport_retries_5xx_then_succeeds(scripted_server):
    scripted_server.script[:] = [
        (503, {}, b""),
        (200, {}, b"recovered"),
    ]
    port = scripted_server.server_address[1]
    sleeps = []

    class TopRng:  # pin the jitter to the ladder's envelope
        def uniform(self, _low, high):
            return high

    assert send("GET", f"http://127.0.0.1:{port}/x",
                sleep=sleeps.append, rng=TopRng()) == b"recovered"
    assert sleeps == [0.5]


# -- GCS batch deletes --------------------------------------------------------


def test_batch_delete_many_objects_few_round_trips(loopback):
    backend = _backend(loopback, prefix="task-3")
    keys = [f"d/{i:03d}" for i in range(250)]
    for key in keys:
        backend.write(key, b"z")
    backend.delete_batch(keys + ["never-existed"])  # 404 subop is success
    assert backend.list() == []
    assert loopback.batch_calls == 3  # ceil(251/100), not 251 DELETEs


def test_batch_delete_retries_failed_subops_individually(loopback):
    backend = _backend(loopback, prefix="t")
    keys = ["k/0", "k/1", "k/2"]
    for key in keys:
        backend.write(key, b"v")

    original_request = backend._request

    def fake_batch_request(method, url, data=None, headers=None,
                           ok_statuses=()):
        if not url.endswith("/batch/storage/v1"):
            # The single-delete fallback uses the real transport.
            return original_request(method, url, data=data, headers=headers,
                                    ok_statuses=ok_statuses)
        return (b"--b\r\n"
                b"Content-Type: application/http\r\n"
                b"Content-ID: <response-1>\r\n\r\n"
                b"HTTP/1.1 204 No Content\r\n\r\n\r\n"
                b"--b\r\n"
                b"Content-Type: application/http\r\n"
                b"Content-ID: <response-2>\r\n\r\n"
                b"HTTP/1.1 500 Backend Error\r\n\r\n\r\n"
                b"--b\r\n"
                b"Content-Type: application/http\r\n"
                b"Content-ID: <response-3>\r\n\r\n"
                b"HTTP/1.1 204 No Content\r\n\r\n\r\n"
                b"--b--")

    deleted = []
    original_delete = backend.delete
    backend._request = fake_batch_request
    backend.delete = lambda key: (deleted.append(key), original_delete(key))
    backend.delete_batch(keys)
    # Only the 500'd suboperation goes through the single-delete ladder.
    assert deleted == ["k/1"]
    assert "t/k/1" not in loopback.objects


def test_batch_delete_unparseable_response_falls_back_to_singles(loopback):
    backend = _backend(loopback, prefix="t2")
    keys = ["a", "b", "c"]
    for key in keys:
        backend.write(key, b"v")

    backend._request = lambda *args, **kwargs: b"not multipart at all"
    deleted = []
    backend.delete = deleted.append
    backend.delete_batch(keys)
    assert sorted(deleted) == keys  # nothing silently assumed deleted


def test_delete_storage_uses_batch_endpoint(loopback, monkeypatch):
    import importlib

    sync_module = importlib.import_module("tpu_task.storage.sync")
    backend = _backend(loopback, prefix="task-7")
    for i in range(120):
        backend.write(f"out/{i:03d}", b"x")
    monkeypatch.setattr(sync_module, "open_backend",
                        lambda remote: (backend, None))
    sync_module.delete_storage(":googlecloudstorage:bkt/task-7")
    assert [k for k in loopback.objects if k.startswith("task-7/")] == []
    assert loopback.batch_calls == 2  # 120 keys → 2 batch round-trips
