"""O(changes) steady state: manifest-planned sync, conditional (ETag/304)
polling, and incremental log tailing — emulator-counter-verified.

The bucket is the only orchestrator↔worker channel, so every loop tick is
paid in REST round-trips. These tests pin the steady-state cost model:

* a no-change ``sync`` tick performs **zero** object-store round-trips;
* a changed tick touches only the diff (one PUT per changed file, one
  DELETE per removed file, no listings);
* an unchanged status/log poll costs the listing only — zero per-blob
  requests, zero body bytes — and a grown log blob fetches just the
  ``Range: bytes={offset}-`` delta;
* the planner self-heals against out-of-band bucket mutation on its
  reconcile tick, and planned syncs produce the exact end state full
  syncs do under randomized churn.
"""

import importlib
import json
import os
import random
import time

import pytest

from tpu_task.storage.backends import GCSBackend, NOT_MODIFIED
from tpu_task.storage.cloud_backends import AzureBlobBackend, S3Backend
from tpu_task.storage.gcs_emulator import LoopbackGCS
from tpu_task.storage.object_store_emulators import (
    LoopbackAzureBlob,
    LoopbackS3,
)

sync_mod = importlib.import_module("tpu_task.storage.sync")

REMOTE = ":googlecloudstorage:steady-bkt"


@pytest.fixture(autouse=True)
def fresh_steady_state():
    """Planner manifests and poll caches are keyed by remote string —
    reset them so reused connection strings never leak state between
    tests."""
    sync_mod.reset_sync_planners()
    sync_mod.reset_poll_caches()
    yield
    sync_mod.reset_sync_planners()
    sync_mod.reset_poll_caches()


@pytest.fixture
def gcs_remote(monkeypatch):
    """A loopback-GCS-backed remote routed under the sync engine's
    ``open_backend`` seam; yields (server, backend)."""
    with LoopbackGCS() as server:
        backend = GCSBackend("steady-bkt")
        server.attach(backend)
        real = sync_mod.open_backend

        def route(remote):
            if remote == REMOTE:
                return backend, None
            return real(remote)

        monkeypatch.setattr(sync_mod, "open_backend", route)
        yield server, backend


def _workdir(tmp_path, n_files=12):
    work = tmp_path / "work"
    (work / "sub").mkdir(parents=True)
    for index in range(n_files):
        (work / f"f{index:02d}.txt").write_text(f"payload {index}")
    (work / "sub" / "nested.txt").write_text("nested")
    return work


# --- tentpole: zero-round-trip no-change ticks -------------------------------

@pytest.mark.perf
def test_no_change_sync_tick_is_zero_round_trips(tmp_path, gcs_remote):
    """Tier-1 perf smoke: the steady-state contract. A regression that
    re-lists (or re-uploads) on an unchanged tick fails here fast."""
    server, _backend = gcs_remote
    work = _workdir(tmp_path)
    sync_mod.sync(str(work), REMOTE)
    assert len(server.objects) == 13

    server.reset_counters()
    sync_mod.sync(str(work), REMOTE)  # no change → planner skips the remote
    assert server.request_total() == 0, server.requests
    assert server.bytes_in == 0 and server.bytes_out == 0


def test_changed_tick_touches_only_the_diff(tmp_path, gcs_remote):
    server, _backend = gcs_remote
    work = _workdir(tmp_path)
    sync_mod.sync(str(work), REMOTE)

    time.sleep(0.01)  # past mtime granularity
    (work / "f00.txt").write_text("changed payload")
    server.reset_counters()
    sync_mod.sync(str(work), REMOTE)
    assert server.requests == {"PUT": 1}, server.requests

    (work / "f01.txt").unlink()
    server.reset_counters()
    sync_mod.sync(str(work), REMOTE)
    assert server.requests == {"DELETE": 1}, server.requests
    assert "f01.txt" not in server.objects


def test_planned_tick_skips_files_already_uploaded_out_of_band(tmp_path,
                                                               gcs_remote):
    """An AsyncCheckpointer direct-uploads each published step off the sync
    tick; the file then appears locally with no manifest entry. The planned
    tick must probe (one scoped listing), see it durable, and NOT re-upload
    a checkpoint-sized object."""
    server, backend = gcs_remote
    work = _workdir(tmp_path, n_files=3)
    sync_mod.sync(str(work), REMOTE)

    # Direct upload (bucket first), then the local file appears — mtime
    # earlier than the upload, exactly the AsyncCheckpointer shape.
    (work / "ckpt-000007.npz").write_bytes(b"c" * 4096)
    backend.write_from_file("ckpt-000007.npz", str(work / "ckpt-000007.npz"))
    server.reset_counters()
    sync_mod.sync(str(work), REMOTE)
    assert server.requests.get("PUT", 0) == 0, server.requests
    assert server.requests.get("LIST") == 1  # the scoped probe

    # And the NEXT no-change tick is back to zero round-trips.
    server.reset_counters()
    sync_mod.sync(str(work), REMOTE)
    assert server.request_total() == 0, server.requests


def test_reconcile_tick_heals_out_of_band_mutation(tmp_path, gcs_remote,
                                                   monkeypatch):
    """Mutate the bucket behind the planner's back (foreign write + foreign
    delete): planned ticks cannot see it, the periodic reconcile tick
    restores an exact mirror."""
    monkeypatch.setenv("TPU_TASK_SYNC_RECONCILE_EVERY", "2")
    server, backend = gcs_remote
    work = _workdir(tmp_path, n_files=4)
    sync_mod.sync(str(work), REMOTE)  # full tick 1 (seeds manifest)

    backend.write("foreign.bin", b"out-of-band write")
    backend.delete("f00.txt")

    sync_mod.sync(str(work), REMOTE)  # planned tick: blind to the mutation
    assert "foreign.bin" in server.objects
    assert "f00.txt" not in server.objects

    sync_mod.sync(str(work), REMOTE)  # planned tick 2
    sync_mod.sync(str(work), REMOTE)  # reconcile: full both-sides listing
    assert "foreign.bin" not in server.objects
    assert server.objects["f00.txt"] == b"payload 0"
    expected = {f"f{i:02d}.txt" for i in range(4)} | {"sub/nested.txt"}
    assert set(server.objects) == expected


def test_planned_sync_failure_invalidates_manifest(tmp_path, gcs_remote,
                                                   monkeypatch):
    """A failed tick leaves the remote state unknown: the next tick must
    re-list instead of trusting the manifest (on-error self-heal)."""
    server, _backend = gcs_remote
    work = _workdir(tmp_path, n_files=3)
    sync_mod.sync(str(work), REMOTE)

    time.sleep(0.01)
    (work / "f00.txt").write_text("will fail then succeed")
    real_copy = sync_mod._copy_files
    calls = {"n": 0}

    def flaky_copy(source, destination, keys, src_meta=None):
        calls["n"] += 1
        if calls["n"] == 1 and keys:
            raise OSError("chaos: transient upload fault")
        return real_copy(source, destination, keys, src_meta)

    monkeypatch.setattr(sync_mod, "_copy_files", flaky_copy)
    with pytest.raises(OSError):
        sync_mod.sync(str(work), REMOTE)
    server.reset_counters()
    sync_mod.sync(str(work), REMOTE)  # full (re-listing) tick after error
    assert server.requests.get("LIST", 0) >= 1
    assert server.objects["f00.txt"] == b"will fail then succeed"


def test_planned_and_full_sync_converge_under_random_churn(tmp_path,
                                                           monkeypatch):
    """Property test: after every churn step, a planner-driven mirror and a
    full-listing mirror of the same source hold identical end states."""
    rng = random.Random(20260804)
    src = tmp_path / "src"
    src.mkdir()
    planned_dst = tmp_path / "planned"
    full_dst = tmp_path / "full"
    monkeypatch.setenv("TPU_TASK_SYNC_RECONCILE_EVERY", "1000000")

    def tree(root):
        out = {}
        for dirpath, _dirs, files in os.walk(root):
            for name in files:
                path = os.path.join(dirpath, name)
                out[os.path.relpath(path, root)] = open(path, "rb").read()
        return out

    names = [f"d{i % 3}/file{i:02d}.bin" for i in range(14)]
    for step in range(12):
        for _ in range(rng.randint(1, 4)):
            name = rng.choice(names)
            path = src / name
            verb = rng.random()
            if verb < 0.55:  # write / rewrite
                path.parent.mkdir(parents=True, exist_ok=True)
                path.write_bytes(os.urandom(rng.randint(0, 64)))
            elif path.exists():  # delete
                path.unlink()
        time.sleep(0.003)  # churn mtimes past the comparison tolerance
        sync_mod.sync(str(src), str(planned_dst))      # planner engaged
        with monkeypatch.context() as patch:
            patch.setenv("TPU_TASK_SYNC_PLANNER", "0")  # pre-PR full path
            sync_mod.sync(str(src), str(full_dst))
        assert tree(planned_dst) == tree(full_dst) == tree(src), \
            f"diverged at churn step {step}"


# --- tentpole: conditional reads across every backend ------------------------

def _conditional_contract(server, backend):
    backend.write("reports/status-m0", b'{"code": "0"}')
    data, validator = backend.read_conditional("reports/status-m0")
    assert data == b'{"code": "0"}' and validator is not None

    server.reset_counters()
    again = backend.read_conditional("reports/status-m0", validator)
    assert again[0] is NOT_MODIFIED
    assert server.requests.get("not_modified") == 1  # one 304...
    assert server.bytes_out == 0                     # ...with no body

    backend.write("reports/status-m0", b'{"code": "1"}')
    changed, fresh = backend.read_conditional("reports/status-m0", validator)
    assert changed == b'{"code": "1"}' and fresh != validator


def _tail_contract(backend):
    backend.write("reports/task-m0", b"line one\n")
    assert backend.read_range("reports/task-m0", 0) == b"line one\n"
    backend.write("reports/task-m0", b"line one\nline two\n")
    assert backend.read_range("reports/task-m0", 9) == b"line two\n"
    assert backend.read_range("reports/task-m0", 18) == b""  # nothing new
    assert backend.read_range("reports/task-m0", 999) == b""  # past EOF


def test_gcs_conditional_and_ranged_reads():
    with LoopbackGCS() as server:
        backend = GCSBackend("bkt")
        server.attach(backend)
        _conditional_contract(server, backend)
        _tail_contract(backend)


def test_s3_conditional_and_ranged_reads():
    with LoopbackS3() as server:
        backend = S3Backend("bkt", config={
            "access_key_id": "AKID", "secret_access_key": "sk",
            "region": "us-east-1"})
        server.attach(backend)
        _conditional_contract(server, backend)
        _tail_contract(backend)


def test_azure_conditional_and_ranged_reads():
    with LoopbackAzureBlob() as server:
        backend = AzureBlobBackend("bkt", config={
            "account": "acct", "key": "a2V5c2VjcmV0"})
        server.attach(backend)
        _conditional_contract(server, backend)
        _tail_contract(backend)


def test_local_conditional_read_is_one_stat(tmp_path):
    from tpu_task.storage.backends import LocalBackend

    backend = LocalBackend(str(tmp_path))
    backend.write("reports/status-m0", b"body")
    data, validator = backend.read_conditional("reports/status-m0")
    assert data == b"body"
    assert backend.read_conditional(
        "reports/status-m0", validator)[0] is NOT_MODIFIED
    time.sleep(0.01)
    backend.write("reports/status-m0", b"body two")
    changed, fresh = backend.read_conditional("reports/status-m0", validator)
    assert changed == b"body two" and fresh != validator


# --- tentpole: poll cache behind reports()/logs()/status() -------------------

@pytest.mark.perf
def test_unchanged_status_and_log_poll_is_listing_only(gcs_remote):
    """32-machine poll: the first tick reads every blob; an unchanged tick
    costs the listing alone — 0 GETs, 0 body bytes (≤1 conditional request
    per blob is the ceiling; the listing validator gets it to zero)."""
    server, backend = gcs_remote
    for index in range(32):
        backend.write(f"reports/status-m{index:02d}",
                      json.dumps({"code": "0"}).encode())
        backend.write(f"reports/task-m{index:02d}",
                      f"machine {index} output\n".encode())

    first = sync_mod.status(REMOTE)
    assert first[list(first)[0]] == 32
    sync_mod.logs(REMOTE)

    server.reset_counters()
    folded = sync_mod.status(REMOTE)
    logs = sync_mod.logs(REMOTE)
    assert len(logs) == 32
    assert folded[list(folded)[0]] == 32
    assert server.requests.get("GET", 0) == 0, server.requests
    assert server.requests.get("LIST") == 2  # one listing per poll surface
    # Listing JSON only (~85 bytes/item × 64 items × 2 sweeps) — no blob
    # body was transferred on top of it.
    assert server.bytes_out < 16384


def test_grown_log_blob_fetches_only_the_delta(gcs_remote):
    server, backend = gcs_remote
    prefix = b"x" * 4096
    backend.write("reports/task-m00", prefix)
    assert sync_mod.logs(REMOTE) == [prefix.decode()]

    backend.write("reports/task-m00", prefix + b"DELTA\n")
    server.reset_counters()
    assert sync_mod.logs(REMOTE) == [(prefix + b"DELTA\n").decode()]
    assert server.requests.get("GET") == 1
    # The ranged read shipped the 6-byte delta plus the TAIL_ANCHOR
    # verification bytes, not the 4 KiB prefix.
    anchor = sync_mod.RemotePollCache.TAIL_ANCHOR
    listing_only = server.bytes_out - 6 - anchor
    assert listing_only < 2048, server.bytes_out


def test_restarted_log_blob_falls_back_to_full_read(gcs_remote):
    """A requeued incarnation rewrites its log from scratch (shorter blob):
    the tail path must detect the shrink and re-read in full."""
    server, backend = gcs_remote
    backend.write("reports/task-m00", b"old incarnation, long output\n")
    sync_mod.logs(REMOTE)
    backend.write("reports/task-m00", b"fresh start\n")
    assert sync_mod.logs(REMOTE) == ["fresh start\n"]


def test_rewritten_longer_log_blob_is_not_spliced(gcs_remote):
    """A restarted incarnation may replay output FASTER than the poll
    period, leaving the rewritten blob longer than the reader's cached
    body: the tail anchor must catch the rewrite — never splice the new
    suffix onto the old prefix."""
    server, backend = gcs_remote
    backend.write("reports/task-m00", b"OLD incarnation line\n")
    sync_mod.logs(REMOTE)
    rewritten = b"NEW incarnation: " + b"x" * 64 + b"\n"
    assert len(rewritten) > len(b"OLD incarnation line\n")
    backend.write("reports/task-m00", rewritten)
    assert sync_mod.logs(REMOTE) == [rewritten.decode()]


def test_same_size_rewritten_log_blob_is_reread(gcs_remote):
    """Same-length rewrite (pathological restart): an unchanged size does
    not prove unchanged content — the conditional read must notice."""
    server, backend = gcs_remote
    backend.write("reports/task-m00", b"aaaa-incarnation-one\n")
    sync_mod.logs(REMOTE)
    backend.write("reports/task-m00", b"bbbb-incarnation-two\n")
    assert sync_mod.logs(REMOTE) == ["bbbb-incarnation-two\n"]


def test_poll_cache_evicts_deleted_reports(gcs_remote):
    server, backend = gcs_remote
    backend.write("reports/status-m0", b'{"code": "0"}')
    backend.write("reports/status-m1", b'{"code": "0"}')
    sync_mod.status(REMOTE)
    backend.delete("reports/status-m1")
    folded = sync_mod.status(REMOTE)
    assert folded[list(folded)[0]] == 1
    cache = sync_mod.poll_cache(REMOTE)
    assert "reports/status-m1" not in cache._entries


def test_poll_cache_disabled_knob(gcs_remote, monkeypatch):
    """TPU_TASK_POLL_CACHE=0 is the escape hatch (and the bench's pre-PR
    measurement path): every poll re-reads every blob."""
    monkeypatch.setenv("TPU_TASK_POLL_CACHE", "0")
    server, backend = gcs_remote
    backend.write("reports/status-m0", b'{"code": "0"}')
    sync_mod.status(REMOTE)
    server.reset_counters()
    sync_mod.status(REMOTE)
    assert server.requests.get("GET") == 1  # unconditional re-read


# --- agent side: append-only log upload --------------------------------------

def test_agent_log_sync_appends_only_the_delta(tmp_path):
    from tpu_task.machine.local_agent import Agent

    agent = Agent(remote=str(tmp_path / "bucket"),
                  directory=str(tmp_path / "work"),
                  script_path="/bin/true", machine_id="m0",
                  timeout_epoch=0, log_period=1, data_period=1)
    agent._append_log("first line\n")
    agent._sync_logs()
    blob = tmp_path / "bucket" / "reports" / "task-m0"
    first = blob.read_bytes()
    assert b"first line" in first

    stamp = blob.stat().st_mtime_ns
    agent._sync_logs()  # nothing appended → no write at all
    assert blob.stat().st_mtime_ns == stamp

    agent._append_log("second line\n")
    agent._sync_logs()
    data = blob.read_bytes()
    assert data.startswith(first) and b"second line" in data

    # Out-of-band truncation (fresh blob after requeue): full rewrite.
    blob.write_bytes(b"")
    agent._append_log("third line\n")
    agent._sync_logs()
    assert b"first line" in blob.read_bytes()
