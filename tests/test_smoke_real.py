"""Real-cloud smoke tests — gated, skipped by default.

The reference's slowest test layer (task/task_smoke_test.go, `make smoke`):
full lifecycle against a REAL control plane with deliberate double-invoke
idempotency, enabled per provider via env vars. Same pattern here:

    SMOKE_TEST_ENABLE_TPU=1 GOOGLE_APPLICATION_CREDENTIALS_DATA='{...}' \
        python -m pytest tests/test_smoke_real.py -m smoke -q

``SMOKE_TEST_SWEEP=1`` deletes any leftover tasks first (the reference's
always-run sweep job, smoke.yml:96-101).
"""

import os
import time
import uuid

import pytest

from tpu_task import task as task_factory
from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import Environment, Size, StatusCode, Task as TaskSpec

pytestmark = pytest.mark.smoke

ENABLED = bool(os.environ.get("SMOKE_TEST_ENABLE_TPU"))
# Inline JSON or a GOOGLE_APPLICATION_CREDENTIALS file path (what CI's OIDC
# auth step provides) both count — from_env handles either.
from tpu_task.common.cloud import GCPCredentials  # noqa: E402

HAS_CREDS = bool(GCPCredentials.from_env().application_credentials)


@pytest.mark.skipif(not (ENABLED and HAS_CREDS),
                    reason="real-TPU smoke disabled (set SMOKE_TEST_ENABLE_TPU "
                           "+ GOOGLE_APPLICATION_CREDENTIALS_DATA)")
def test_tpu_real_lifecycle(tmp_path):
    from tpu_task.common.cloud import Credentials, GCPCredentials

    cloud = Cloud(
        provider=Provider.TPU,
        region=os.environ.get("SMOKE_TEST_TPU_REGION", "us-central2"),
        credentials=Credentials(gcp=GCPCredentials.from_env()),
    )

    if os.environ.get("SMOKE_TEST_SWEEP"):
        for identifier in task_factory.list_tasks(cloud):
            task_factory.new(cloud, identifier, TaskSpec()).delete()

    sentinel = str(uuid.uuid4())
    workdir = tmp_path / "work"
    workdir.mkdir()
    (workdir / "input.txt").write_text("smoke-payload")
    spec = TaskSpec(
        size=Size(machine=os.environ.get("SMOKE_TEST_TPU_MACHINE", "v2-8")),
        environment=Environment(
            script=f"#!/bin/bash\ncat input.txt\necho {sentinel}\n"
                   "mkdir -p output && echo ok > output/r.txt\n",
            directory=str(workdir), directory_out="output",
        ),
    )
    identifier = Identifier.random("smoke")
    task = task_factory.new(cloud, identifier, spec)
    task.delete()            # NotFound tolerated
    task.create()
    task.create()            # double-invoke idempotency (smoke_test.go:180)
    try:
        deadline = time.time() + 25 * 60
        while time.time() < deadline:
            task.read()
            status = task.status()
            if status.get(StatusCode.SUCCEEDED, 0) >= 1:
                break
            assert status.get(StatusCode.FAILED, 0) == 0, task.logs()
            time.sleep(10)
        else:
            raise AssertionError(f"timeout; logs={task.logs()}")
        logs = "".join(task.logs())
        assert sentinel in logs and "smoke-payload" in logs
    finally:
        task.delete()
        task.delete()        # double delete tolerated
    assert (workdir / "output" / "r.txt").exists()
