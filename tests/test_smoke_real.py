"""Real-cloud smoke tests — gated, skipped by default.

The reference's slowest test layer (task/task_smoke_test.go, `make smoke`):
full lifecycle against a REAL control plane with deliberate double-invoke
idempotency, enabled per provider via env vars. Same pattern here:

    SMOKE_TEST_ENABLE_TPU=1 GOOGLE_APPLICATION_CREDENTIALS_DATA='{...}' \
        python -m pytest tests/test_smoke_real.py -m smoke -q

``SMOKE_TEST_SWEEP=1`` deletes any leftover tasks first (the reference's
always-run sweep job, smoke.yml:96-101).
"""

import os
import time
import uuid

import pytest

from tpu_task import task as task_factory
from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import Environment, Size, StatusCode, Task as TaskSpec

pytestmark = pytest.mark.smoke

ENABLED = bool(os.environ.get("SMOKE_TEST_ENABLE_TPU"))
# Inline JSON or a GOOGLE_APPLICATION_CREDENTIALS file path (what CI's OIDC
# auth step provides) both count — from_env handles either.
from tpu_task.common.cloud import GCPCredentials  # noqa: E402

HAS_CREDS = bool(GCPCredentials.from_env().application_credentials)


@pytest.mark.skipif(not (ENABLED and HAS_CREDS),
                    reason="real-TPU smoke disabled (set SMOKE_TEST_ENABLE_TPU "
                           "+ GOOGLE_APPLICATION_CREDENTIALS_DATA)")
def test_tpu_real_lifecycle(tmp_path):
    from tpu_task.common.cloud import Credentials, GCPCredentials

    cloud = Cloud(
        provider=Provider.TPU,
        region=os.environ.get("SMOKE_TEST_TPU_REGION", "us-central2"),
        credentials=Credentials(gcp=GCPCredentials.from_env()),
    )

    if _sweep(cloud):
        return
    _lifecycle(cloud, os.environ.get("SMOKE_TEST_TPU_MACHINE", "v2-8"),
               tmp_path)


# -- per-provider matrix (reference smoke.yml: SMOKE_TEST_ENABLE_{AWS,AZ,GCP}) --


def _sweep(cloud) -> bool:
    """Always-run straggler cleanup (smoke.yml:96-101 role). Returns True in
    sweep mode — the caller must then SKIP its lifecycle: the cleanup job
    exists to delete leaked resources, not to provision new billed ones."""
    if os.environ.get("SMOKE_TEST_SWEEP"):
        for identifier in task_factory.list_tasks(cloud):
            task_factory.new(cloud, identifier, TaskSpec()).delete()
        return True
    return False


def _lifecycle(cloud, machine: str, tmp_path, budget_s: int = 25 * 60):
    """The reference's smoke shape (task_smoke_test.go:162-233): delete →
    create → create (idempotent) → poll logs for a sentinel → delete →
    delete, asserting the output round-trip."""
    sentinel = str(uuid.uuid4())
    workdir = tmp_path / "work"
    workdir.mkdir()
    (workdir / "input.txt").write_text("smoke-payload")
    spec = TaskSpec(
        size=Size(machine=machine),
        environment=Environment(
            script=f"#!/bin/bash\ncat input.txt\necho {sentinel}\n"
                   "mkdir -p output && echo ok > output/r.txt\n",
            directory=str(workdir), directory_out="output",
        ),
    )
    identifier = Identifier.random("smoke")
    task = task_factory.new(cloud, identifier, spec)
    task.delete()
    task.create()
    task.create()
    try:
        deadline = time.time() + budget_s
        while time.time() < deadline:
            task.read()
            status = task.status()
            if status.get(StatusCode.SUCCEEDED, 0) >= 1:
                break
            assert status.get(StatusCode.FAILED, 0) == 0, task.logs()
            time.sleep(10)
        else:
            raise AssertionError(f"timeout; logs={task.logs()}")
        logs = "".join(task.logs())
        assert sentinel in logs and "smoke-payload" in logs
    finally:
        task.delete()
        task.delete()
    assert (workdir / "output" / "r.txt").exists()


@pytest.mark.skipif(
    not (os.environ.get("SMOKE_TEST_ENABLE_AWS")
         and os.environ.get("AWS_ACCESS_KEY_ID")),
    reason="real-AWS smoke disabled (set SMOKE_TEST_ENABLE_AWS + AWS_* creds)")
def test_aws_real_lifecycle(tmp_path):
    from tpu_task.common.cloud import AWSCredentials, Credentials

    cloud = Cloud(provider=Provider.AWS,
                  region=os.environ.get("SMOKE_TEST_AWS_REGION", "us-east-1"),
                  credentials=Credentials(aws=AWSCredentials.from_env()))
    if _sweep(cloud):
        return
    _lifecycle(cloud, os.environ.get("SMOKE_TEST_AWS_MACHINE", "s"), tmp_path)


@pytest.mark.skipif(
    not (os.environ.get("SMOKE_TEST_ENABLE_GCP") and HAS_CREDS),
    reason="real-GCE smoke disabled (set SMOKE_TEST_ENABLE_GCP + GCP creds)")
def test_gce_real_lifecycle(tmp_path):
    from tpu_task.common.cloud import Credentials, GCPCredentials

    cloud = Cloud(provider=Provider.GCP,
                  region=os.environ.get("SMOKE_TEST_GCP_REGION", "us-west1-b"),
                  credentials=Credentials(gcp=GCPCredentials.from_env()))
    if _sweep(cloud):
        return
    _lifecycle(cloud, os.environ.get("SMOKE_TEST_GCP_MACHINE", "s"), tmp_path)


@pytest.mark.skipif(
    not (os.environ.get("SMOKE_TEST_ENABLE_K8S")
         and (os.environ.get("KUBECONFIG")
              or os.environ.get("KUBECONFIG_DATA"))),
    reason="real-K8s smoke disabled (set SMOKE_TEST_ENABLE_K8S + a "
           "kubeconfig; any cluster works — see "
           "docs/guides/testing-kubernetes.md for the kind recipe)")
def test_k8s_real_lifecycle(tmp_path):
    """The one real backend provable without cloud credentials: a kind
    cluster needs only Docker (reference smoke.yml:102-152 runs the same
    lifecycle against a throwaway AKS cluster)."""
    cloud = Cloud(provider=Provider.K8S,
                  region=os.environ.get("SMOKE_TEST_K8S_REGION", ""))
    if _sweep(cloud):
        return
    _lifecycle(cloud, os.environ.get("SMOKE_TEST_K8S_MACHINE", "s"),
               tmp_path, budget_s=10 * 60)


@pytest.mark.skipif(
    not (os.environ.get("SMOKE_TEST_ENABLE_AZ")
         and os.environ.get("AZURE_CLIENT_ID")),
    reason="real-Azure smoke disabled (set SMOKE_TEST_ENABLE_AZ + AZURE_* creds)")
def test_az_real_lifecycle(tmp_path):
    from tpu_task.common.cloud import AZCredentials, Credentials

    cloud = Cloud(provider=Provider.AZ,
                  region=os.environ.get("SMOKE_TEST_AZ_REGION", "eastus"),
                  credentials=Credentials(az=AZCredentials.from_env()))
    if _sweep(cloud):
        return
    _lifecycle(cloud, os.environ.get("SMOKE_TEST_AZ_MACHINE", "s"), tmp_path)
