"""Golden-file tests for the worker bootstrap script renderer
(reference strategy: task/common/machine/script_test.go:14-41 + goldie).

Regenerate goldens with: UPDATE_GOLDEN=1 python -m pytest tests/test_machine_script.py
"""

import os
from datetime import datetime, timezone

import pytest

from tpu_task.common.values import Variables
from tpu_task.machine import render_script

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "testdata")


def check_golden(name: str, content: str):
    path = os.path.join(GOLDEN_DIR, name + ".golden")
    if os.environ.get("UPDATE_GOLDEN"):
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        with open(path, "w") as handle:
            handle.write(content)
    with open(path) as handle:
        assert content == handle.read()


def test_machine_script_minimal():
    script = render_script("\n", {}, Variables(), None)
    check_golden("machine_script_minimal", script)


def test_machine_script_full():
    timeout = datetime(2025, 3, 1, 12, 0, 0, tzinfo=timezone.utc)
    script = render_script(
        "#!/bin/bash\necho hello\n",
        {"TPU_TASK_REMOTE": ":googlecloudstorage:bucket/prefix",
         "TPU_TASK_CLOUD_PROVIDER": "tpu",
         "TPU_TASK_CLOUD_REGION": "us-central2-b",
         "TPU_TASK_IDENTIFIER": "tpi-test-3z4xlzwq-3u0vweb4"},
        Variables({"MY_VAR": 'va"lue'}),
        timeout,
    )
    check_golden("machine_script_full", script)


def test_timeout_embedding():
    timeout = datetime(2025, 3, 1, 12, 0, 0, tzinfo=timezone.utc)
    script = render_script("x", {}, Variables(), timeout)
    assert str(int(timeout.timestamp())) in script
    assert "infinity" not in script.split("RuntimeMaxSec")[0].split("REMAINING")[1]


def test_no_timeout_is_infinity():
    script = render_script("x", {}, Variables(), None)
    assert "$((infinity-$(date +%s)))" in script


def test_credentials_are_shell_escaped():
    script = render_script("x", {"KEY": "va'lue; rm -rf /"}, Variables(), None)
    import base64
    # Extract the credentials payload (third base64 block) and verify quoting.
    blocks = [b.strip() for b in script.split("END")]
    decoded = []
    for block in blocks:
        tail = block.rsplit("\n", 1)[-1]
        try:
            decoded.append(base64.b64decode(tail.encode()).decode())
        except Exception:
            decoded.append("")
    creds = [d for d in decoded if d.startswith("export ")]
    assert creds, "credentials block not found"
    assert creds[0] == "export 'KEY=va'\"'\"'lue; rm -rf /'\n"


def test_worker_zero_guards_self_destruct():
    script = render_script("x", {}, Variables(), None)
    assert 'test "${TPU_WORKER_ID:-0}" != "0"' in script


def test_agent_wheel_url_embedding():
    script = render_script("x", {}, Variables(), None,
                           agent_wheel_url="https://gcs/b/o/agent.whl?alt=media")
    assert 'TPU_TASK_AGENT_WHEEL_URL="https://gcs/b/o/agent.whl?alt=media"' in script
    # No staged wheel → empty URL → bootstrap falls back to the index.
    assert 'TPU_TASK_AGENT_WHEEL_URL=""' in render_script("x", {}, Variables(), None)


def test_agent_wheel_builds_and_stages(tmp_path):
    """The wheel the bootstrap installs must actually build from this
    checkout and stage into a bucket (VERDICT r2 missing #5: the bootstrap
    referenced a nonexistent package)."""
    from tpu_task.machine.wheel import ensure_wheel, stage_wheel

    wheel = ensure_wheel()
    assert wheel is not None and wheel.endswith(".whl")
    assert os.path.exists(wheel)

    url = stage_wheel(str(tmp_path / "bucket"))
    assert url == ""  # local remotes don't produce media URLs
    staged = list((tmp_path / "bucket" / "agent").glob("tpu_task-*.whl"))
    assert len(staged) == 1
    assert staged[0].stat().st_size > 10_000
