"""Production-traffic serving tests: prefix cache, chunked prefill,
speculative decoding (CPU, tiny shapes).

The ``perf``-marked tests are the tier-1 exactness contract of the three
production pieces (docs/parity.md "Serving cost model"):

- greedy token streams are BIT-IDENTICAL with the prefix cache on vs off,
  with chunked prefill vs the legacy bucketed programs, and with
  speculative decoding on vs off;
- a recompute-preempted request replays an identical SAMPLED stream on
  re-admission (the schedule-independence the keyed samplers promise);
- the refcounted allocator's invariants hold under randomized load, and
  copy-on-write never touches a donor block's bytes.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpu_task.ml.models import decoding, transformer
from tpu_task.ml.serving import (
    BlockAllocator,
    ServingConfig,
    ServingEngine,
)
from tpu_task.ml.serving.cache import SCRATCH_BLOCK, PrefixCache
from tpu_task.ml.serving.engine import DrainTimeout

# GQA on purpose, same as test_serving.py: the paged pool stays at
# KV-head width end to end.
TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32, n_kv_heads=2)

# A genuinely smaller draft (own family member: same vocab, fewer layers /
# narrower) — its proposals rarely match the target, exercising rejection.
DRAFT = transformer.TransformerConfig(
    vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_head=8, d_ff=32,
    dtype=jnp.float32, n_kv_heads=2)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def draft_params():
    return transformer.init(jax.random.PRNGKey(7), DRAFT)


def _generate_ref(params, prompt, max_new):
    return list(np.asarray(decoding.generate(
        params, TINY, jnp.asarray(prompt)[None].astype(jnp.int32),
        max_new)[0]))


def _shared_prefix_workload(rng, n=4, shared=12, tail=4):
    head = rng.integers(0, TINY.vocab_size, size=shared)
    return [np.concatenate([head, rng.integers(0, 64, size=tail)])
            for _ in range(n)]


# -- exactness: the three bit-identity contracts -----------------------------

@pytest.mark.perf
def test_chunked_prefill_matches_bucketed_greedy(params):
    """Chunked-vs-bucketed greedy bit-identity: folding the prompt into
    the fused step (any chunk size) produces exactly the tokens the legacy
    whole-prompt bucketed program does — including prompts that span
    several chunks and co-scheduled decoders mid-ingestion."""
    rng = np.random.default_rng(11)
    reqs = [(rng.integers(0, 64, size=plen), new)
            for plen, new in [(5, 6), (13, 4), (16, 8), (3, 5)]]

    def run(**kw):
        scfg = ServingConfig(slots=3, block_size=4, n_blocks=64, max_len=32,
                             prefill_buckets=(8, 16), prefix_cache=False,
                             **kw)
        eng = ServingEngine(params, TINY, scfg)
        rids = [eng.submit(p, n) for p, n in reqs]
        out = eng.drain()
        return [out[r] for r in rids]

    bucketed = run(prefill="bucketed")
    assert bucketed == run(prefill="chunked", chunk_tokens=4)
    assert bucketed == run(prefill="chunked", chunk_tokens=7)   # ragged
    assert bucketed == [_generate_ref(params, p, n) for p, n in reqs]


@pytest.mark.perf
def test_prefix_cache_greedy_identity_and_hits(params):
    """Prefix-cache on/off greedy bit-identity on a shared-prefix workload,
    plus the admission-cost claim: cache-on requests after the first skip
    prefill of every cached full block (tokens_saved counts them)."""
    rng = np.random.default_rng(3)
    prompts = _shared_prefix_workload(rng, n=5, shared=12, tail=4)

    def run(cache):
        scfg = ServingConfig(slots=2, block_size=4, n_blocks=64, max_len=48,
                             prefix_cache=cache)
        eng = ServingEngine(params, TINY, scfg)
        rids = [eng.submit(p, 6) for p in prompts]
        out = eng.drain()
        return [out[r] for r in rids], eng

    cached, eng_on = run(True)
    uncached, eng_off = run(False)
    assert cached == uncached
    assert cached == [_generate_ref(params, p, 6) for p in prompts]
    st = eng_on.stats()["prefix_cache"]
    # 3 shared full blocks (12 tokens / block_size 4); slots=2 means the
    # first two admissions may race, but later ones must hit.
    assert st["hit_requests"] >= 2
    assert st["tokens_saved"] >= 2 * 12
    assert st["blocks_saved"] >= 2 * 3
    assert eng_off.stats()["prefix_cache"]["enabled"] is False
    assert eng_on.allocator.referenced == 0


@pytest.mark.perf
def test_speculative_greedy_identity(params, draft_params):
    """Spec-on/off greedy bit-identity: with ANY draft, the accept rule
    (longest agreeing prefix + bonus) must reproduce non-speculative
    greedy decoding exactly; with the draft = the target itself, every
    proposal agrees and the accept rate pins near 1."""
    rng = np.random.default_rng(5)
    reqs = [(rng.integers(0, 64, size=plen), new)
            for plen, new in [(6, 10), (9, 7), (4, 12)]]

    def run(spec_k, dparams=None, dcfg=None):
        scfg = ServingConfig(slots=2, block_size=4, n_blocks=64, max_len=48,
                             spec_k=spec_k, prefix_cache=False)
        eng = ServingEngine(params, TINY, scfg, draft_params=dparams,
                            draft_cfg=dcfg)
        rids = [eng.submit(p, n) for p, n in reqs]
        out = eng.drain()
        return [out[r] for r in rids], eng

    plain, _ = run(0)
    assert plain == [_generate_ref(params, p, n) for p, n in reqs]
    weak, weak_eng = run(3, draft_params, DRAFT)
    assert weak == plain
    assert weak_eng.stats()["spec"]["proposed"] > 0
    strong, strong_eng = run(3, params, TINY)    # draft == target
    assert strong == plain
    st = strong_eng.stats()["spec"]
    assert st["accept_rate"] > 0.9               # self-draft ≈ always agrees
    assert st["accepted"] > st["rounds"]         # >1 token/round on average


def test_speculative_sampled_is_deterministic_and_schedule_free(
        params, draft_params):
    """Sampled spec decoding draws its accept coins from position-keyed
    per-request streams: the same request produces the same tokens across
    runs and regardless of co-scheduling (slots=1 vs slots=3)."""
    prompts = [np.random.default_rng(9).integers(0, 64, size=6)
               for _ in range(3)]

    def run(slots):
        scfg = ServingConfig(slots=slots, block_size=4, n_blocks=64,
                             max_len=48, spec_k=2, prefix_cache=False)
        eng = ServingEngine(params, TINY, scfg, rng=jax.random.PRNGKey(21),
                            draft_params=draft_params, draft_cfg=DRAFT)
        rids = [eng.submit(p, 8, temperature=0.9, top_p=0.8)
                for p in prompts]
        out = eng.drain()
        return [out[r] for r in rids]

    first = run(1)
    assert first == run(1) == run(3)
    assert all(len(s) == 8 for s in first)


# -- satellite: drain() must not silently return partial results -------------

def test_drain_timeout_raises_with_unfinished_ids(params):
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=32, max_len=32)
    eng = ServingEngine(params, TINY, scfg)
    a = eng.submit(np.zeros((4,), np.int32), 20)
    b = eng.submit(np.ones((4,), np.int32), 20)
    with pytest.raises(DrainTimeout) as exc:
        eng.drain(max_steps=3)
    assert exc.value.unfinished == [a, b]
    assert str(a) in str(exc.value) and "3" in str(exc.value)
    # The engine is still usable: a full drain finishes the same requests.
    out = eng.drain()
    assert len(out[a]) == 20 and len(out[b]) == 20


# -- satellite: preemption replays an identical sampled stream ---------------

def test_preemption_replays_identical_sampled_stream(params):
    """A slot preempted mid-decode and re-admitted must reproduce the SAME
    sampled tokens as an unpreempted run: the fold_in(request_key,
    token_index) keys claim schedule independence, and this pins it across
    recompute preemption (spec-decode rollback relies on the same
    property)."""
    prompts = [np.random.default_rng(13).integers(0, 64, size=6)
               for _ in range(4)]

    def run(n_blocks):
        scfg = ServingConfig(slots=4, block_size=4, n_blocks=n_blocks,
                             max_len=24, prefix_cache=False)
        eng = ServingEngine(params, TINY, scfg, rng=jax.random.PRNGKey(2))
        rids = [eng.submit(p, 12, temperature=0.8, top_p=0.9)
                for p in prompts]
        out = eng.drain()
        pre = sum(eng.request(r).preemptions for r in rids)
        return [out[r] for r in rids], pre

    tight, tight_pre = run(10)      # pool too small → recompute preemption
    roomy, roomy_pre = run(64)
    assert tight_pre > 0 and roomy_pre == 0
    assert tight == roomy


@pytest.mark.perf
def test_exported_inflight_resumes_identical_sampled_stream(params):
    """export_inflight → JSON → resume_inflight in a FRESH engine must
    continue every sampled stream token-identically to the uninterrupted
    run: the PR 8 preemption-replay pin extended across process
    boundaries (the serve subsystem's graceful-drain contract — a drained
    replica's in-flight requests complete on a sibling with no visible
    seam). The export is round-tripped through json to pin
    serializability, and one request is re-preempted AFTER resume to pin
    that recompute rolls back to the imported prefix, never through it."""
    import json

    rng = np.random.default_rng(23)
    prompts = [rng.integers(0, 64, size=7) for _ in range(3)]

    def mk(n_blocks=64):
        scfg = ServingConfig(slots=3, block_size=4, n_blocks=n_blocks,
                             max_len=32)
        return ServingEngine(params, TINY, scfg, rng=jax.random.PRNGKey(5))

    reference = mk()
    ref_rids = [reference.submit(p, 14, temperature=0.9, top_p=0.85)
                for p in prompts]
    ref_out = reference.drain()

    first = mk()
    rids = [first.submit(p, 14, temperature=0.9, top_p=0.85)
            for p in prompts]
    for _ in range(6):                       # partway through every stream
        first.step()
    records = json.loads(json.dumps(first.export_inflight()))
    assert records and all(r["key"] and len(r["key"]) >= 2 for r in records)
    assert any(0 < len(r["tokens"]) < 14 for r in records), \
        "export caught nothing mid-stream"

    second = mk(n_blocks=12)                 # tight pool: forces recompute
    mapping = second.resume_inflight(records)
    out = second.drain()
    resumed_preempts = sum(
        second.request(mapping[r]).preemptions for r in mapping)
    for i, rid in enumerate(rids):
        if rid in mapping:
            full = out[mapping[rid]]
        else:                                # finished before the export
            full = first.poll(rid)["tokens"]
        assert full == ref_out[ref_rids[i]], i
    # The tight pool really did preempt a resumed slot (rolling back to
    # the imported prefix) and the streams STILL match — resume_from held.
    assert resumed_preempts > 0


def test_bucketed_resume_outgrowing_buckets_recomputes_identically(params):
    """Bucketed engines pad prompt + resumed prefix into ONE bucket; a
    context that outgrew every bucket must fall back to recomputing from
    the prompt (the keyed streams regenerate the identical prefix) rather
    than rejecting a request that was valid at submit time — a rejection
    would terminally fail the fleet router's failover."""
    prompt = np.random.default_rng(31).integers(0, 64, size=14)

    def mk():
        scfg = ServingConfig(slots=2, block_size=4, n_blocks=64, max_len=32,
                             prefill="bucketed", prefill_buckets=(8, 16),
                             prefix_cache=False)
        return ServingEngine(params, TINY, scfg, rng=jax.random.PRNGKey(9))

    reference = mk()
    ref_rid = reference.submit(prompt, 10, temperature=0.7, top_p=0.9)
    ref_out = reference.drain()[ref_rid]

    first = mk()
    rid = first.submit(prompt, 10, temperature=0.7, top_p=0.9)
    for _ in range(5):
        first.step()
    records = first.export_inflight()
    assert records and len(records[0]["tokens"]) >= 3  # 14 + 3 > bucket 16
    second = mk()
    mapping = second.resume_inflight(records)
    assert second.drain()[mapping[rid]] == ref_out


# -- satellite: refcounted-allocator property tests --------------------------

def _check_invariants(alloc: BlockAllocator):
    free = set(alloc._free)
    referenced = set(alloc._ref)
    retained = set(alloc._retained)
    assert all(c >= 1 for c in alloc._ref.values())      # never negative/zero
    assert not free & referenced      # never simultaneously free + referenced
    assert not free & retained        # never simultaneously free + retained
    assert SCRATCH_BLOCK not in free | referenced | retained
    # Conservation: every block is free, referenced, or retained-at-ref-0.
    assert len(free) + len(referenced | retained) == alloc.n_blocks - 1


def test_allocator_refcount_properties_randomized():
    """Randomized op soak over alloc/incref/decref/retain/release: the
    documented invariants hold after every operation — refcounts never
    negative, no block both free and referenced (or free and retained),
    conservation of blocks."""
    rng = np.random.default_rng(0)
    alloc = BlockAllocator(24)
    live: list = []
    retained: list = []
    for _ in range(2000):
        op = rng.integers(0, 5)
        if op == 0:
            got = alloc.alloc(int(rng.integers(1, 4)))
            if got is not None:
                live += got
        elif op == 1 and live:
            alloc.incref(live[int(rng.integers(len(live)))])
        elif op == 2 and live:
            b = live[int(rng.integers(len(live)))]
            if alloc.decref(b) == 0:
                live = [x for x in live if x != b]
        elif op == 3 and live:
            b = live[int(rng.integers(len(live)))]
            if not alloc.is_retained(b):
                alloc.retain(b)
                retained.append(b)
        elif op == 4 and retained:
            b = retained.pop(int(rng.integers(len(retained))))
            if alloc.is_retained(b):
                alloc.release(b)
        _check_invariants(alloc)
    # API misuse raises instead of corrupting (fresh allocator: the soak
    # may have drained the free list).
    alloc = BlockAllocator(4)
    with pytest.raises(ValueError, match="unreferenced"):
        alloc.decref(alloc._free[-1])
    with pytest.raises(ValueError, match="free"):
        alloc.incref(alloc._free[-1])
    with pytest.raises(ValueError, match="invalid"):
        alloc.decref(SCRATCH_BLOCK)
    with pytest.raises(ValueError, match="free"):
        alloc.retain(alloc._free[-1])
    (b,) = alloc.alloc(1)
    with pytest.raises(ValueError, match="unretained"):
        alloc.release(b)


def test_prefix_cache_eviction_reclaims_only_refcount_zero_lru():
    """Eviction reclaims exactly the refcount-0 cached blocks, LRU first;
    referenced cache entries are never touched."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, block_size=2)
    # Three one-block "retired prompts" registered in order a, b, c.
    entries = {}
    for name, toks in [("a", [1, 2]), ("b", [3, 4]), ("c", [5, 6])]:
        (blk,) = alloc.alloc(1)
        cache.register(np.asarray(toks, np.int32), [blk])
        alloc.decref(blk)           # drops to ref 0, stays retained
        entries[name] = blk
    # A lookup references "b" (and LRU-touches it).
    got = cache.lookup(np.asarray([3, 4], np.int32))
    assert got == [entries["b"]]
    assert alloc.refcount(entries["b"]) == 1
    # Evicting 2 reclaims a then c (LRU order skips the referenced b).
    assert cache.evict(2) == 2
    assert alloc.is_free(entries["a"]) and alloc.is_free(entries["c"])
    assert not alloc.is_free(entries["b"])
    assert cache.evict(5) == 0      # nothing evictable left
    assert len(cache) == 1
    _check_invariants(alloc)


def test_cow_leaves_donor_block_bytes_identical(params):
    """Copy-on-write: re-submitting a fully-cached prompt makes the new
    slot COW the final shared block before rewriting its last position —
    the donor block's bytes in every layer's pool must be byte-identical
    before and after, and the replayed stream must still match."""
    scfg = ServingConfig(slots=1, block_size=4, n_blocks=32, max_len=32)
    eng = ServingEngine(params, TINY, scfg)
    prompt = np.random.default_rng(17).integers(0, 64, size=8)  # 2 full blocks
    first_rid = eng.submit(prompt, 4)
    first = eng.drain()[first_rid]
    # The prompt's two full blocks are now cached at refcount 0; snapshot
    # the whole pool, then replay the identical prompt (whole-prompt hit →
    # COW of the final shared block).
    donor_pools = [{k: np.asarray(v) for k, v in layer.items()}
                   for layer in eng.pools]
    # 8 prompt tokens + 3 written generated positions (the last emitted
    # token's KV is never written) = the prompt's 2 full blocks register.
    cached_blocks = sorted(eng._pcache._hash_of)
    assert len(cached_blocks) == 2
    second_rid = eng.submit(prompt, 4)
    second = eng.drain()[second_rid]
    assert second == first
    assert eng.cow_copies == 1
    st = eng.stats()["prefix_cache"]
    assert st["tokens_saved"] == 7      # plen-1: last token recomputed
    after = eng.pools
    for layer_before, layer_after in zip(donor_pools, after):
        for k in ("k", "v"):
            got = np.asarray(layer_after[k])
            for b in cached_blocks:
                np.testing.assert_array_equal(layer_before[k][b], got[b])


def test_cache_eviction_never_causes_extra_preemption(params):
    """LRU eviction only when the free list runs dry: a workload that an
    uncached engine completes without preemption must also run
    preemption-free with the cache on — retained blocks yield (evictions)
    instead of forcing recompute."""
    rng = np.random.default_rng(23)
    prompts = [rng.integers(0, 64, size=8) for _ in range(6)]

    def run(cache):
        scfg = ServingConfig(slots=2, block_size=4, n_blocks=14, max_len=24,
                             prefix_cache=cache)
        eng = ServingEngine(params, TINY, scfg)
        rids = [eng.submit(p, 6) for p in prompts]
        out = eng.drain()
        return [out[r] for r in rids], eng

    uncached, eng_off = run(False)
    cached, eng_on = run(True)
    assert cached == uncached
    assert eng_off.preemption_count == 0
    assert eng_on.preemption_count == 0          # the no-harm contract
    assert eng_on.stats()["prefix_cache"]["evictions"] > 0


# -- chunked prefill: the no-stall property (functional, not timing) ---------

def test_long_admission_does_not_stall_running_slot(params):
    """While a long prompt ingests chunk by chunk, an already-running slot
    must emit a token EVERY step (the Sarathi property). The bucketed
    baseline admits with a whole-prompt program instead — its running slot
    sees zero tokens during that admission stall."""
    long_prompt = np.random.default_rng(29).integers(0, 64, size=32)

    def run(prefill):
        scfg = ServingConfig(
            slots=2, block_size=4, n_blocks=64, max_len=64,
            prefill=prefill, chunk_tokens=4, prefix_cache=False,
            prefill_buckets=(8, 32))
        eng = ServingEngine(params, TINY, scfg)
        running = eng.submit(np.arange(4, dtype=np.int32), 40)
        eng.step()                   # running slot admitted + first token
        before = len(eng.poll(running)["tokens"])
        chunks_before = eng.prefill_chunks
        long_rid = eng.submit(long_prompt, 4)
        # Step until the long request emits ITS first token; every one of
        # those scheduler steps must also advance the running slot.
        steps = 0
        while not eng.poll(long_rid)["tokens"]:
            eng.step()
            steps += 1
        gained = len(eng.poll(running)["tokens"]) - before
        return steps, gained, eng.prefill_chunks - chunks_before

    steps, gained, chunks = run("chunked")
    # 32-token prompt at chunk 4 = 8 fused steps, a running-slot token each.
    assert steps == 8 and gained == 8 and chunks == 8
    steps, gained, _chunks = run("bucketed")
    # The legacy path ingests the whole prompt inside ONE scheduler step:
    # the running slot sees a single token across the entire admission —
    # in wall-time, a full-prompt stall (the bench measures it as p99
    # inter-token latency).
    assert steps == 1 and gained == 1


def test_chunked_admits_prompts_longer_than_any_bucket(params):
    """Chunked prefill has no bucket ceiling: a prompt longer than the
    largest legacy bucket admits fine (only max_len bounds it)."""
    scfg = ServingConfig(slots=1, block_size=4, n_blocks=64, max_len=64,
                         prefill_buckets=(8,), prefix_cache=False)
    eng = ServingEngine(params, TINY, scfg)
    prompt = np.random.default_rng(31).integers(0, 64, size=40)
    rid = eng.submit(prompt, 5)
    out = eng.drain()[rid]
    assert out == _generate_ref(params, prompt, 5)


# -- config validation for the new knobs -------------------------------------

def test_production_config_validation(params, draft_params):
    with pytest.raises(ValueError, match="chunk_tokens"):
        ServingConfig(chunk_tokens=0)
    with pytest.raises(ValueError, match="spec_k"):
        ServingConfig(spec_k=-1)
    with pytest.raises(ValueError, match="prefill"):
        ServingConfig(prefill="streaming")
    with pytest.raises(ValueError, match="prefix_cache"):
        ServingConfig(prefill="bucketed", prefix_cache=True)
    with pytest.raises(ValueError, match="draft"):
        ServingEngine(params, TINY, ServingConfig(spec_k=2))
    big_vocab = transformer.TransformerConfig(
        vocab_size=128, d_model=16, n_layers=1, n_heads=2, d_head=8,
        d_ff=32, dtype=jnp.float32, n_kv_heads=2)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(params, TINY, ServingConfig(spec_k=2),
                      draft_params=transformer.init(
                          jax.random.PRNGKey(0), big_vocab),
                      draft_cfg=big_vocab)


def test_stats_exposes_production_counters(params):
    """The bench scenarios read these keys; pin their presence and basic
    sanity so a stats() refactor cannot silently break `bench.py serving`."""
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=32, max_len=32)
    eng = ServingEngine(params, TINY, scfg)
    prompt = np.random.default_rng(37).integers(0, 64, size=8)
    eng.submit(prompt, 4)
    eng.drain()
    eng.submit(prompt, 4)
    eng.drain()
    st = eng.stats()
    pc = st["prefix_cache"]
    assert pc["enabled"] and pc["hit_requests"] == 1
    assert pc["tokens_saved"] == 7 and pc["blocks_saved"] == 2
    assert pc["cow_copies"] == 1 and pc["cached_blocks"] >= 2
    assert st["recompute_preemptions"] == 0
    assert st["chunk_steps"] > 0 and st["prefill_chunks"] > 0
    assert st["spec"] == {"k": 0, "rounds": 0, "proposed": 0,
                          "accepted": 0, "accept_rate": 0.0}
