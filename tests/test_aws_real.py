"""Real-mode AWS backend against scripted Query-API transports.

Covers VERDICT r2 row 13: the EC2 + Auto Scaling control plane over SigV4
Query calls — resource DAG composition (task/aws/task.go:28-196), ASG
MixedInstancesPolicy spot semantics (resource_auto_scaling_group.go:51-106),
image grammar (data_source_image.go), security-group rules, and the Read
aggregation into Status/Addresses/Events.
"""

import json
import urllib.parse

import pytest

from test_http_resilience import FakeSleep, FakeTransport

from tpu_task.backends.aws.api import QueryClient, member_list
from tpu_task.common.cloud import AWSCredentials, Cloud, Credentials, Provider
from tpu_task.common.errors import (
    ResourceAlreadyExistsError,
    ResourceNotFoundError,
)
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import Environment, Size, Spot, Task as TaskSpec

NOT_FOUND_LT = ("http", 400, {}, b"<Response><Errors><Error><Code>"
                b"InvalidLaunchTemplateName.NotFoundException</Code>"
                b"<Message>nope</Message></Error></Errors></Response>")


def _cloud():
    return Cloud(provider=Provider.AWS, region="us-east-1",
                 credentials=Credentials(aws=AWSCredentials(
                     access_key_id="AKIDEXAMPLE",
                     secret_access_key="secret")))


def _form(request) -> dict:
    return dict(urllib.parse.parse_qsl(request.data.decode()))


def _real_task(spec=None):
    from tpu_task.backends.aws.task import AWSRealTask

    task = AWSRealTask(_cloud(), Identifier.deterministic("awsreal"),
                       spec or TaskSpec())
    for client in (task.ec2, task.asg_client):
        client._sleep = FakeSleep()
    return task


# -- factory routing ----------------------------------------------------------


def test_factory_routes_to_real_aws_with_credentials(monkeypatch):
    from tpu_task.backends.aws.task import AWSRealTask, new_aws_task

    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    task = new_aws_task(_cloud(), Identifier.deterministic("t"), TaskSpec())
    assert isinstance(task, AWSRealTask)


def test_factory_stays_hermetic_without_credentials(monkeypatch):
    from tpu_task.backends.aws.task import AWSTask, new_aws_task

    monkeypatch.delenv("TPU_TASK_FAKE_TPU_ROOT", raising=False)
    task = new_aws_task(Cloud(provider=Provider.AWS, region="us-east-1"),
                        Identifier.deterministic("t"), TaskSpec())
    assert isinstance(task, AWSTask)


# -- Query client -------------------------------------------------------------


def test_query_client_signs_and_parses():
    client = QueryClient("ec2", "2016-11-15", "us-east-1", "AKIDEXAMPLE", "sk")
    transport = FakeTransport([
        ("ok", b"<DescribeVpcsResponse><vpcSet><item><vpcId>vpc-9</vpcId>"
               b"</item></vpcSet></DescribeVpcsResponse>")])
    client._urlopen = transport
    client._sleep = FakeSleep()
    root = client.call("DescribeVpcs")
    assert root.find(".//vpcId").text == "vpc-9"
    request = transport.requests[0]
    assert request.get_header("Authorization").startswith(
        "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
    form = _form(request)
    assert form["Action"] == "DescribeVpcs"
    assert form["Version"] == "2016-11-15"


def test_query_client_maps_error_codes():
    client = QueryClient("autoscaling", "2011-01-01", "us-east-1", "A", "S")
    client._sleep = FakeSleep()
    client._urlopen = FakeTransport([
        ("http", 400, {}, b"<ErrorResponse><Error><Code>AlreadyExists</Code>"
                          b"<Message>dup</Message></Error></ErrorResponse>")])
    with pytest.raises(ResourceAlreadyExistsError):
        client.call("CreateAutoScalingGroup")
    client._urlopen = FakeTransport([NOT_FOUND_LT])
    with pytest.raises(ResourceNotFoundError):
        client.call("DescribeLaunchTemplateVersions")


def test_member_list_encodings():
    assert member_list("InstanceId", ["i-1", "i-2"]) == {
        "InstanceId.1": "i-1", "InstanceId.2": "i-2"}
    assert member_list("Names", ["x"], member=True) == {"Names.member.1": "x"}


# -- resources ----------------------------------------------------------------


def test_image_picks_newest(monkeypatch):
    from tpu_task.backends.aws.resources import Image

    client = QueryClient("ec2", "2016-11-15", "us-east-1", "A", "S")
    client._sleep = FakeSleep()
    client._urlopen = FakeTransport([
        ("ok", b"<r><imagesSet>"
               b"<item><imageId>ami-old</imageId>"
               b"<creationDate>2024-01-01T00:00:00.000Z</creationDate></item>"
               b"<item><imageId>ami-new</imageId>"
               b"<creationDate>2025-06-01T00:00:00.000Z</creationDate></item>"
               b"</imagesSet></r>")])
    image = Image(client, "")
    image.read()
    assert image.image_id == "ami-new"
    assert image.ssh_user == "ubuntu"
    form = _form(client._urlopen.requests[0])
    assert form["Filter.1.Name"] == "name"
    assert form["Filter.2.Name"] == "state"
    assert form["Filter.2.Value.1"] == "available"
    assert form["Filter.4.Name"] == "owner-id"
    assert form["Filter.4.Value.1"] == "099720109477"


def test_image_bad_grammar_raises():
    from tpu_task.backends.aws.resources import Image

    client = QueryClient("ec2", "2016-11-15", "us-east-1", "A", "S")
    with pytest.raises(ValueError, match="image"):
        Image(client, "not-a-spec").read()


def test_asg_spot_semantics():
    from tpu_task.backends.aws.resources import AutoScalingGroup

    def created_form(spot):
        asg = QueryClient("autoscaling", "2011-01-01", "us-east-1", "A", "S")
        asg._sleep = FakeSleep()
        asg._urlopen = FakeTransport([("ok", b"<r/>")])
        group = AutoScalingGroup(asg, None, "tpi-x", launch_template="tpi-x",
                                 subnet_ids=["s-1"], parallelism=3, spot=spot)
        group.create()
        return _form(asg._urlopen.requests[0])

    bid = created_form(0.5)
    assert bid["MixedInstancesPolicy.InstancesDistribution."
               "SpotMaxPrice"] == "0.50000"
    assert bid["MixedInstancesPolicy.InstancesDistribution."
               "OnDemandPercentageAboveBaseCapacity"] == "0"
    auto = created_form(0.0)
    assert "MixedInstancesPolicy.InstancesDistribution.SpotMaxPrice" not in auto
    assert auto["MixedInstancesPolicy.InstancesDistribution."
                "OnDemandPercentageAboveBaseCapacity"] == "0"
    on_demand = created_form(-1.0)
    assert on_demand["MixedInstancesPolicy.InstancesDistribution."
                     "OnDemandPercentageAboveBaseCapacity"] == "100"
    assert bid["MaxSize"] == "3" and bid["DesiredCapacity"] == "0"


def test_security_group_rule_plan():
    from tpu_task.backends.aws.resources import DefaultVpc, SecurityGroup
    from tpu_task.common.values import Firewall, FirewallRule

    client = QueryClient("ec2", "2016-11-15", "us-east-1", "A", "S")
    client._sleep = FakeSleep()
    client._urlopen = FakeTransport([
        ("ok", b"<r><groupId>sg-7</groupId></r>"),  # create
        ("ok", b"<r/>"),  # revoke default egress
        ("ok", b"<r/>"),  # self ingress
        ("ok", b"<r/>"),  # self egress
        ("ok", b"<r/>"),  # port 22 ingress (tcp+udp)
        ("ok", b"<r/>"),  # egress allow-all
    ])
    vpc = DefaultVpc(client)
    vpc.vpc_id = "vpc-1"
    group = SecurityGroup(client, "tpi-x", vpc,
                          Firewall(ingress=FirewallRule(ports=[22])))
    group.create()
    forms = [_form(r) for r in client._urlopen.requests]
    assert forms[0]["Action"] == "CreateSecurityGroup"
    assert forms[1]["Action"] == "RevokeSecurityGroupEgress"
    assert forms[2]["IpPermissions.1.UserIdGroupPairs.1.GroupId"] == "sg-7"
    assert forms[4]["IpPermissions.1.FromPort"] == "22"
    assert forms[4]["IpPermissions.2.IpProtocol"] == "udp"
    assert forms[5]["Action"] == "AuthorizeSecurityGroupEgress"
    assert forms[5]["IpPermissions.1.IpProtocol"] == "-1"


# -- lifecycle ----------------------------------------------------------------


def test_create_issues_full_resource_plan(monkeypatch):
    spec = TaskSpec(size=Size(machine="m+t4", storage=120),
                    environment=Environment(script="#!/bin/sh\ntrue"),
                    spot=Spot(0))
    task = _real_task(spec)
    task.bucket.create = lambda: None  # S3 exercised in loopback tests
    monkeypatch.setattr("tpu_task.machine.wheel.stage_wheel", lambda remote: "")
    ec2_script = FakeTransport([
        ("ok", b"<r><vpcSet><item><vpcId>vpc-1</vpcId></item></vpcSet></r>"),
        ("ok", b"<r><subnetSet><item><subnetId>subnet-a</subnetId></item>"
               b"<item><subnetId>subnet-b</subnetId></item></subnetSet></r>"),
        ("ok", b"<r><imagesSet><item><imageId>ami-1</imageId>"
               b"<creationDate>2025-01-01T00:00:00Z</creationDate></item>"
               b"</imagesSet></r>"),
        ("ok", b"<r><groupId>sg-1</groupId></r>"),   # SG create
        ("ok", b"<r/>"), ("ok", b"<r/>"), ("ok", b"<r/>"),
        ("ok", b"<r/>"), ("ok", b"<r/>"),            # SG rules
        ("ok", b"<r/>"),                             # ImportKeyPair
        NOT_FOUND_LT,                                # recorded-remote probe
        ("ok", b"<r/>"),                             # CreateLaunchTemplate
    ])
    asg_script = FakeTransport([
        ("ok", b"<r/>"),                             # CreateAutoScalingGroup
        ("ok", b"<r/>"),                             # SetDesiredCapacity
    ])
    task.ec2._urlopen = ec2_script
    task.asg_client._urlopen = asg_script
    task.create()

    lt_form = _form(ec2_script.requests[-1])
    assert lt_form["Action"] == "CreateLaunchTemplate"
    assert lt_form["LaunchTemplateData.InstanceType"] == "g4dn.xlarge"
    assert lt_form["LaunchTemplateData.ImageId"] == "ami-1"
    assert lt_form["LaunchTemplateData.BlockDeviceMapping.1.Ebs."
                   "VolumeSize"] == "120"
    assert lt_form["LaunchTemplateData.TagSpecification.1.Tag.1."
                   "Key"] == "tpu-task-remote"
    # The recorded remote is SANITIZED: no credentials in EC2 tags.
    tag_value = lt_form["LaunchTemplateData.TagSpecification.1.Tag.1.Value"]
    assert "secret" not in tag_value and "AKIDEXAMPLE" not in tag_value
    assert tag_value.startswith(":s3,")
    asg_form = _form(asg_script.requests[0])
    assert asg_form["VPCZoneIdentifier"] == "subnet-a,subnet-b"
    assert asg_form["MaxSize"] == "1"
    resize_form = _form(asg_script.requests[1])
    assert resize_form["Action"] == "SetDesiredCapacity"
    assert resize_form["DesiredCapacity"] == "1"


def test_read_aggregates_addresses_status_events(monkeypatch):
    task = _real_task(TaskSpec())
    task.asg_client._urlopen = FakeTransport([
        ("ok", b"<r><AutoScalingGroups><member>"
               b"<DesiredCapacity>2</DesiredCapacity>"
               b"<Instances><member><InstanceId>i-1</InstanceId></member>"
               b"<member><InstanceId>i-2</InstanceId></member></Instances>"
               b"</member></AutoScalingGroups></r>"),
        ("ok", b"<r><Activities><member>"
               b"<StatusCode>Successful</StatusCode>"
               b"<StartTime>2026-07-29T00:00:00Z</StartTime>"
               b"<Cause>scale out</Cause><Description>launch i-1"
               b"</Description></member></Activities></r>"),
    ])
    task.ec2._urlopen = FakeTransport([
        ("ok", b"<r><reservationSet><item><instancesSet>"
               b"<item><instanceState><name>running</name></instanceState>"
               b"<ipAddress>54.1.2.3</ipAddress></item>"
               b"<item><instanceState><name>pending</name></instanceState>"
               b"</item></instancesSet></item></reservationSet></r>"),
        NOT_FOUND_LT,  # recorded-remote probe in _folded_status
    ])
    monkeypatch.setattr("tpu_task.backends.gcs_remote.storage_status",
                        lambda remote, initial=None: initial)
    task.read()
    from tpu_task.common.values import StatusCode

    assert task.get_addresses() == ["54.1.2.3"]
    assert task.spec.status == {StatusCode.ACTIVE: 1}
    assert task.spec.events[0].code == "Successful"
    assert task.observed_parallelism() == 2


def test_delete_tolerates_missing_resources():
    task = _real_task(TaskSpec())
    task.bucket.delete = lambda: None
    task.ec2._urlopen = FakeTransport([
        NOT_FOUND_LT,    # recorded-remote probe
        NOT_FOUND_LT,    # DeleteLaunchTemplate
        ("http", 400, {}, b"<R><Errors><Error><Code>InvalidKeyPair.NotFound"
                          b"</Code></Error></Errors></R>"),
        ("ok", b"<r><securityGroupInfo/></r>"),  # SG read: no group
    ])
    task.asg_client._urlopen = FakeTransport([
        ("http", 400, {}, b"<R><Error><Code>ValidationError</Code>"
                          b"<Message>not found</Message></Error></R>"),
    ])
    task.delete()  # no raise: fully idempotent


def test_bare_read_recovers_recorded_remote_from_tags():
    """A fresh task (empty spec) resolves its storage from the launch
    template's tags — tasks created with --storage-container are observed
    at the right bucket."""
    task = _real_task(TaskSpec())
    task.ec2._urlopen = FakeTransport([
        ("ok", b"<r><launchTemplateVersionSet><item><launchTemplateData>"
               b"<tagSpecificationSet><item><tagSet><item>"
               b"<key>tpu-task-remote</key>"
               b"<value>:s3,region='us-east-1':shared/runs-7</value>"
               b"</item></tagSet></item></tagSpecificationSet>"
               b"</launchTemplateData></item></launchTemplateVersionSet></r>"),
    ])
    # The sanitized record comes back with THIS process's credentials
    # re-injected (the record itself carries none).
    assert task._remote() == (":s3,access_key_id='AKIDEXAMPLE',"
                              "region='us-east-1',"
                              "secret_access_key='secret':shared/runs-7")
