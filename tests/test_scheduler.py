"""Gang scheduler: queues/quotas, gang admission, bin-packing, fair share.

Model-level tests run on the virtual clock with :class:`SimGangDriver` —
no processes, no wall-clock — so the property-based sweeps (randomized gang
sizes/priorities/seeds) are fast enough for tier 1. The real-task
integration test (scheduler-initiated preemption riding the PR 3 requeue
governor of live fake-mode agents) is marked ``slow`` and runs under
``make sched-soak``.
"""

import json
import os
import random

import pytest

import bench
from tpu_task.cli.main import main as cli_main
from tpu_task.scheduler import (
    CapacityPool,
    DurableQueue,
    GangScheduler,
    GangSpec,
    QueuedTask,
    SimGangDriver,
    TenantQuota,
    TpuTaskDriver,
)
from tpu_task.scheduler.pool import select_victims
from tpu_task.scheduler.queue import fair_share_order

pytestmark = pytest.mark.scheduler


def make_sched(pool, quotas, remote=None, checkpoint_period=0.0):
    """Scheduler + sim driver on one shared virtual clock."""
    now = [0.0]
    clock = lambda: now[0]  # noqa: E731
    driver = SimGangDriver(clock=clock, checkpoint_period=checkpoint_period)
    scheduler = GangScheduler(pool, quotas, driver, remote=remote, clock=clock)
    return scheduler, driver, now


def drain(scheduler, now, dt=0.5, limit=10_000):
    ticks = 0
    while not scheduler.idle():
        scheduler.tick()
        now[0] += dt
        ticks += 1
        assert ticks < limit, "scheduler did not converge"
    return ticks


# -- gang admission: all-or-nothing -------------------------------------------


def test_gang_admission_is_all_or_nothing():
    """A gang that cannot fully fit must hold NOTHING — no partial slices
    camping on capacity (v4-16 = 8 chips per slice)."""
    pool = CapacityPool([8, 4])
    task = QueuedTask(task_id="g", tenant="a",
                      gang=GangSpec("v4-16", slices=2))
    assert pool.try_place(task) is None
    assert pool.free == [8, 4]          # untouched
    assert pool.placements == {}
    one = QueuedTask(task_id="h", tenant="a", gang=GangSpec("v4-16", slices=1))
    placement = pool.try_place(one)
    assert placement is not None and placement.total_chips == 8


def test_slice_never_spans_domains():
    """One v4-32 slice needs 16 contiguous chips; two half-empty domains
    don't add up — a TPU slice cannot span pods."""
    pool = CapacityPool([8, 8])
    assert not pool.ever_fits(GangSpec("v4-32", slices=1))
    assert pool.ever_fits(GangSpec("v4-16", slices=2))


def test_best_fit_keeps_large_holes_open():
    pool = CapacityPool([16, 4])
    small = QueuedTask(task_id="s", tenant="a", gang=GangSpec("v4-8"))
    placement = pool.try_place(small)
    assert placement.domains == [1]     # tightest feasible domain first
    big = QueuedTask(task_id="b", tenant="a", gang=GangSpec("v4-32"))
    assert pool.try_place(big) is not None  # the 16-hole survived


def test_pool_placement_property_never_exceeds_capacity():
    """Property sweep: random place/release traffic never overcommits a
    domain, and every placement is whole-gang (slices × chips accounted)."""
    for seed in range(20):
        rng = random.Random(seed)
        domains = [rng.choice([8, 16, 32]) for _ in range(rng.randint(1, 5))]
        pool = CapacityPool(domains)
        live = {}
        for step in range(200):
            if live and rng.random() < 0.4:
                task_id = rng.choice(sorted(live))
                pool.release(task_id)
                del live[task_id]
            else:
                gang = GangSpec(rng.choice(["v4-8", "v4-16", "v4-32"]),
                                slices=rng.randint(1, 3))
                task = QueuedTask(task_id=f"t{seed}-{step}", tenant="a",
                                  gang=gang)
                placement = pool.try_place(task)
                if placement is None:
                    continue
                assert len(placement.domains) == gang.slices
                live[task.task_id] = placement
            assert all(chips >= 0 for chips in pool.free)
            assert sum(pool.capacity) - sum(pool.free) == sum(
                placement.total_chips for placement in pool.placements.values())
        assert set(pool.placements) == set(live)


# -- fair-share ordering -------------------------------------------------------


def test_fair_share_orders_most_deficient_tenant_first():
    tasks = [
        QueuedTask(task_id="a1", tenant="a", gang=GangSpec("v4-8"), submit_seq=0),
        QueuedTask(task_id="b1", tenant="b", gang=GangSpec("v4-8"), submit_seq=1),
    ]
    order = fair_share_order(tasks, {"a": 32, "b": 0}, {"a": 1.0, "b": 1.0})
    assert [task.task_id for task in order] == ["b1", "a1"]
    # Weight scales the entitlement: a at 32 chips with weight 8 is LESS
    # loaded than b at 8 chips with weight 1.
    order = fair_share_order(tasks, {"a": 32, "b": 8}, {"a": 8.0, "b": 1.0})
    assert [task.task_id for task in order] == ["a1", "b1"]


def test_priority_then_fifo_within_tenant():
    tasks = [
        QueuedTask(task_id="lo", tenant="a", gang=GangSpec("v4-8"),
                   priority=0, submit_seq=0),
        QueuedTask(task_id="hi", tenant="a", gang=GangSpec("v4-8"),
                   priority=2, submit_seq=1),
        QueuedTask(task_id="hi2", tenant="a", gang=GangSpec("v4-8"),
                   priority=2, submit_seq=2),
    ]
    order = fair_share_order(tasks, {}, {"a": 1.0})
    assert [task.task_id for task in order] == ["hi", "hi2", "lo"]


def test_scheduling_is_deterministic_for_a_fixed_seed():
    """Two full runs from one seed produce identical placement histories —
    the property that makes a failing soak replayable."""

    def run(seed):
        rng = random.Random(seed)
        pool = CapacityPool([32, 32])
        quotas = {"a": TenantQuota(chips=48, weight=2.0),
                  "b": TenantQuota(chips=48, weight=1.0)}
        scheduler, driver, now = make_sched(pool, quotas)
        history = []
        for index in range(30):
            scheduler.submit(rng.choice(["a", "b"]),
                             rng.choice(["v4-8", "v4-16", "v4-32"]),
                             slices=rng.randint(1, 2),
                             priority=rng.randrange(3),
                             work=rng.uniform(1, 6), task_id=f"t{index}")
        ticks = 0
        while not scheduler.idle() and ticks < 5000:
            scheduler.tick()
            history.append(sorted(task.task_id
                                  for task in scheduler.queue.placed()))
            now[0] += 0.5
            ticks += 1
        assert scheduler.idle()
        return history

    assert run(7) == run(7)
    assert run(7) != run(8)  # the seed actually drives the workload


# -- quotas --------------------------------------------------------------------


def test_quota_chips_and_max_tasks_never_exceeded():
    for seed in range(5):
        rng = random.Random(seed)
        pool = CapacityPool([64, 64])
        quotas = {"a": TenantQuota(chips=48, max_tasks=3, weight=1.0),
                  "b": TenantQuota(chips=32, max_tasks=2, weight=1.0)}
        scheduler, driver, now = make_sched(pool, quotas)
        for index in range(40):
            scheduler.submit(rng.choice(["a", "b"]),
                             rng.choice(["v4-8", "v4-16"]),
                             priority=rng.randrange(3),
                             work=rng.uniform(1, 4), task_id=f"q{seed}-{index}")
        ticks = 0
        while not scheduler.idle() and ticks < 5000:
            scheduler.tick()
            for tenant, quota in quotas.items():
                assert scheduler.queue.running_chips(tenant) <= quota.chips
                assert scheduler.queue.running_tasks(tenant) <= quota.max_tasks
            now[0] += 0.5
            ticks += 1
        assert scheduler.idle()


def test_submit_rejects_impossible_gangs():
    pool = CapacityPool([16])
    scheduler, _, _ = make_sched(pool, {"a": TenantQuota(chips=8),
                                        "big": TenantQuota(chips=64)})
    with pytest.raises(ValueError, match="quota"):
        scheduler.submit("a", "v4-32")        # 16 chips > 8-chip quota
    with pytest.raises(ValueError, match="cannot fit"):
        scheduler.submit("big", "v4-16", slices=3)  # 24 chips > 16-chip pool
    with pytest.raises(ValueError, match="unknown tenant"):
        scheduler.submit("nobody", "v4-8")


# -- preemption ----------------------------------------------------------------


def _placed(task_id, tenant, priority, placed_at, pool, accelerator="v4-8"):
    task = QueuedTask(task_id=task_id, tenant=tenant,
                      gang=GangSpec(accelerator), priority=priority,
                      state="placed", placed_at=placed_at)
    assert pool.try_place(task) is not None
    return task


def test_victim_order_over_share_then_priority_then_youngest():
    """The documented victim order: over-share tenants' excess gangs first
    (youngest placement first), then strictly-lower-priority gangs of
    under-share tenants; a tenant's entitled share is never reclaimed on
    fairness grounds."""
    pool = CapacityPool([20])
    victims_pool = [
        _placed("over-old", "glut", priority=1, placed_at=1.0, pool=pool),
        _placed("over-mid", "glut", priority=1, placed_at=3.0, pool=pool),
        _placed("over-young", "glut", priority=1, placed_at=5.0, pool=pool),
        _placed("under-lo", "lean", priority=0, placed_at=2.0, pool=pool),
        _placed("under-hi", "lean", priority=2, placed_at=2.0, pool=pool),
    ]
    candidate = QueuedTask(task_id="new", tenant="starved",
                           gang=GangSpec("v4-8"), priority=1)
    # glut runs 12 chips against a 2-chip share (10 excess — two of its
    # three gangs are reclaimable before it hits its entitled floor);
    # starved runs 0 against 6 (deficient candidate).
    running = {"glut": 12, "lean": 8, "starved": 0}
    shares = {"glut": 2.0, "lean": 8.0, "starved": 6.0}
    victims = select_victims(candidate, victims_pool, pool, running, shares)
    assert [victim.task_id for victim in victims] == ["over-young"]
    # Two slices: both excess gangs, youngest first.
    candidate2 = QueuedTask(task_id="new2", tenant="starved",
                            gang=GangSpec("v4-8", slices=2), priority=1)
    victims = select_victims(candidate2, victims_pool, pool, running, shares)
    assert [victim.task_id for victim in victims] == ["over-young", "over-mid"]
    # Three slices: glut's remaining gang IS its entitled share (4-4 < 2
    # would breach the floor) — the under-share class opens instead, but
    # ONLY strictly lower priority (under-lo at 0 < 1).
    candidate3 = QueuedTask(task_id="new3", tenant="starved",
                            gang=GangSpec("v4-8", slices=3), priority=1)
    victims = select_victims(candidate3, victims_pool, pool, running, shares)
    assert [victim.task_id for victim in victims] == [
        "over-young", "over-mid", "under-lo"]
    # Four slices: under-hi at priority 2 is untouchable and over-old is
    # floor-protected — no sufficient set exists, so NOBODY is preempted.
    candidate4 = QueuedTask(task_id="new4", tenant="starved",
                            gang=GangSpec("v4-8", slices=4), priority=1)
    assert select_victims(candidate4, victims_pool, pool, running,
                          shares) == []


def test_over_share_reclaim_takes_only_the_excess():
    """A tenant whose share is smaller than one gang is NOT reclaimable on
    fairness grounds — evicting its only gang cannot improve fairness, it
    just flips the starvation (the cross-tenant ping-pong guard)."""
    pool = CapacityPool([4])
    holder = _placed("only", "a", priority=1, placed_at=1.0, pool=pool)
    candidate = QueuedTask(task_id="new", tenant="b",
                           gang=GangSpec("v4-8"), priority=1)
    running = {"a": 4, "b": 0}
    shares = {"a": 2.0, "b": 2.0}  # share < gang: excess is negative
    assert select_victims(candidate, [holder], pool, running, shares) == []
    # A strictly higher-priority candidate still wins (priority class).
    vip = QueuedTask(task_id="vip", tenant="b",
                     gang=GangSpec("v4-8"), priority=2)
    victims = select_victims(vip, [holder], pool, running, shares)
    assert [victim.task_id for victim in victims] == ["only"]


def test_victim_set_is_minimal():
    """A victim whose domain turned out not to help is NOT preempted."""
    pool = CapacityPool([8, 16])
    small = _placed("small", "glut", priority=0, placed_at=9.0, pool=pool)
    assert pool.placements["small"].domains == [0]  # best fit → 8-domain
    big = _placed("big", "glut", priority=0, placed_at=1.0, pool=pool,
                  accelerator="v4-32")
    candidate = QueuedTask(task_id="new", tenant="lean",
                           gang=GangSpec("v4-32"), priority=0)
    victims = select_victims(candidate, [small, big], pool,
                             {"glut": 20, "lean": 0},
                             {"glut": 2.0, "lean": 18.0})
    # Order alone would take small (youngest) first, but only big's 16-chip
    # domain can host a v4-32 slice — small must survive.
    assert [victim.task_id for victim in victims] == ["big"]


def test_scheduler_preemption_charges_no_budget_chaos_does():
    """Scheduler-initiated preemption is policy (no budget charge, no
    backoff); a chaos reclaim burns the gang's recovery budget and
    converges to a durable recovery-budget-exhausted failure."""
    os.environ["TPU_TASK_RECOVERY_BUDGET"] = "2"
    os.environ["TPU_TASK_REQUEUE_BACKOFF_BASE"] = "0.5"
    try:
        pool = CapacityPool([8])
        quotas = {"a": TenantQuota(chips=8, weight=1.0),
                  "b": TenantQuota(chips=8, weight=1.0)}
        scheduler, driver, now = make_sched(pool, quotas)
        victim = scheduler.submit("a", "v4-16", work=100.0, task_id="victim")
        scheduler.tick()
        assert victim.state == "placed"
        # Higher-priority arrival preempts it (strictly higher priority).
        scheduler.submit("b", "v4-16", priority=3, work=1.0, task_id="vip")
        scheduler.tick()
        assert victim.state == "preempted"
        assert victim.attempts == 0           # no budget charged
        assert victim.next_eligible_at <= now[0]  # no backoff either
        assert scheduler.queue.tasks["vip"].state == "placed"
        # Drain vip; victim comes back, then chaos kills it repeatedly.
        now[0] += 2.0
        scheduler.tick()
        assert victim.state == "placed"
        for expected_attempts in (1, 2):
            driver.kill("victim")
            scheduler.tick()
            assert victim.state == "preempted"
            assert victim.attempts == expected_attempts
            assert victim.next_eligible_at > now[0]  # backoff gate
            now[0] = victim.next_eligible_at + 0.1
            scheduler.tick()
            assert victim.state == "placed"
        driver.kill("victim")
        scheduler.tick()                      # third chaos kill: budget gone
        assert victim.state == "failed"
        assert victim.failure == "recovery-budget-exhausted"
    finally:
        os.environ.pop("TPU_TASK_RECOVERY_BUDGET", None)
        os.environ.pop("TPU_TASK_REQUEUE_BACKOFF_BASE", None)


def test_preempted_gang_resumes_from_checkpoint_not_scratch():
    pool = CapacityPool([8])
    scheduler, driver, now = make_sched(
        pool, {"a": TenantQuota(chips=8)}, checkpoint_period=1.0)
    task = scheduler.submit("a", "v4-8", work=10.0, task_id="ckpt")
    scheduler.tick()
    now[0] = 5.7
    driver.kill("ckpt", graceful=False)       # hard kill mid-checkpoint
    scheduler.tick()
    assert task.state == "preempted"
    assert task.progress == 5.0               # floor to checkpoint boundary
    now[0] = 8.0
    scheduler.tick()                          # backoff elapsed → re-placed
    assert task.state == "placed"
    now[0] = 13.5                             # 5.0 done + 5.5 > remaining 5
    scheduler.tick()
    assert task.state == "succeeded"


def test_scheduler_graceful_preemption_checkpoints_progress():
    """A scheduler-evicted victim resumes from "now", not from scratch:
    the checkpoint must land inside the driver's preempt() because the
    scheduler requeues the victim without an intervening poll()."""
    pool = CapacityPool([8])
    quotas = {"a": TenantQuota(chips=8, weight=1.0),
              "b": TenantQuota(chips=8, weight=1.0)}
    scheduler, driver, now = make_sched(pool, quotas, checkpoint_period=1.0)
    victim = scheduler.submit("a", "v4-16", work=100.0, task_id="victim")
    scheduler.tick()
    assert victim.state == "placed"
    now[0] = 50.0
    scheduler.submit("b", "v4-16", priority=3, work=1.0, task_id="vip")
    scheduler.tick()                          # graceful scheduler eviction
    assert victim.state == "preempted"
    assert victim.progress == 50.0            # graceful: no floor, no loss


def test_tpu_driver_failure_reason_reads_the_durable_record():
    """A plain nonzero-exit script failure is labeled task-failed; only a
    durable recovery-budget-exhausted event earns that failure code (the
    status fold alone cannot tell the two apart)."""

    class _Event:
        def __init__(self, code):
            self.code = code

    class _Backend:
        def __init__(self, codes):
            self._codes = codes

        def events(self):
            return [_Event(code) for code in self._codes]

    task = QueuedTask(task_id="t", tenant="a", gang=GangSpec("v4-8"),
                      submitted_at=0.0)
    plain = TpuTaskDriver(lambda _task: _Backend(["recover"]))
    assert plain.failure_reason(task) == "task-failed"
    exhausted = TpuTaskDriver(
        lambda _task: _Backend(["recover", "recovery-budget-exhausted"]))
    assert exhausted.failure_reason(task) == "recovery-budget-exhausted"


# -- fair-share requeue after chaos -------------------------------------------


def test_freed_capacity_reoffered_by_deficit_not_fifo():
    """Tenant a floods the queue first; when capacity frees, the offer goes
    to the most-deficient tenant (b), not the oldest submission."""
    pool = CapacityPool([16])
    quotas = {"a": TenantQuota(chips=16, weight=1.0),
              "b": TenantQuota(chips=16, weight=1.0)}
    scheduler, driver, now = make_sched(pool, quotas)
    for index in range(4):                    # a's backlog: FIFO would win
        scheduler.submit("a", "v4-16", work=4.0, task_id=f"a{index}")
    scheduler.tick()
    assert {task.task_id for task in scheduler.queue.placed()} == {"a0", "a1"}
    scheduler.submit("b", "v4-16", work=4.0, task_id="b0")
    scheduler.tick()
    # b is owed half the pool; a is over share → one a gang is preempted
    # and the freed slot goes to b ahead of a's older backlog.
    placed = {task.task_id for task in scheduler.queue.placed()}
    assert "b0" in placed
    assert len([task_id for task_id in placed if task_id.startswith("a")]) == 1
    # The preempted a gang kept its queue position among a's backlog: when
    # b finishes, a's oldest schedulable gang goes first.
    now[0] += 5.0
    scheduler.tick()
    placed = {task.task_id for task in scheduler.queue.placed()}
    assert "a1" in placed or "a0" in placed


def test_chaos_freed_capacity_cannot_starve_a_tenant():
    """One tenant's flaky workload (every gang chaos-killed once) must not
    starve the other: both tenants' work completes and the stable tenant's
    deficit stays bounded by one gang."""
    os.environ["TPU_TASK_REQUEUE_BACKOFF_BASE"] = "0.2"
    try:
        pool = CapacityPool([16])
        quotas = {"flaky": TenantQuota(chips=16, weight=1.0),
                  "stable": TenantQuota(chips=16, weight=1.0)}
        scheduler, driver, now = make_sched(pool, quotas)
        rng = random.Random(3)
        for index in range(6):
            scheduler.submit("flaky", "v4-8", work=2.0, task_id=f"f{index}")
            scheduler.submit("stable", "v4-8", work=2.0, task_id=f"s{index}")
        killed = set()
        ticks = 0
        while not scheduler.idle() and ticks < 2000:
            for task_id in driver.running_ids():
                if task_id.startswith("f") and task_id not in killed \
                        and rng.random() < 0.5:
                    driver.kill(task_id)
                    killed.add(task_id)
            scheduler.tick()
            now[0] += 0.25
            ticks += 1
        assert scheduler.idle()
        states = {task.task_id: task.state
                  for task in scheduler.queue.tasks.values()}
        assert all(state == "succeeded" for state in states.values()), states
        assert scheduler.max_deficit.get("stable", 0.0) <= 8.0  # one gang
    finally:
        os.environ.pop("TPU_TASK_REQUEUE_BACKOFF_BASE", None)


# -- durability ----------------------------------------------------------------


def test_queue_survives_scheduler_restart(tmp_path):
    remote = str(tmp_path / "sched")
    pool = CapacityPool([8])
    quotas = {"a": TenantQuota(chips=8)}
    scheduler, driver, now = make_sched(pool, quotas, remote=remote)
    for index in range(4):
        scheduler.submit("a", "v4-8", priority=index % 2, work=3.0,
                         task_id=f"t{index}")
    scheduler.tick()
    placed_before = sorted(task.task_id for task in scheduler.queue.placed())
    # A fresh scheduler process: same remote, empty memory. Placed records
    # demote to preempted (their sim state died with the process) and the
    # whole backlog—states, priorities, FIFO order—survives.
    scheduler2, driver2, now2 = make_sched(CapacityPool([8]), quotas,
                                           remote=remote)
    assert sorted(scheduler2.queue.tasks) == ["t0", "t1", "t2", "t3"]
    assert sorted(task.task_id for task in scheduler2.queue.tasks.values()
                  if task.state == "preempted") == placed_before
    seqs = {task.task_id: task.submit_seq
            for task in scheduler2.queue.tasks.values()}
    assert seqs == {"t0": 0, "t1": 1, "t2": 2, "t3": 3}
    drain(scheduler2, now2)
    assert all(task.state == "succeeded"
               for task in scheduler2.queue.tasks.values())
    # Late-arriving submissions continue the sequence — no reordering.
    late = scheduler2.submit("a", "v4-8", work=1.0, task_id="late")
    assert late.submit_seq == 4


def test_durable_queue_rejects_duplicate_ids(tmp_path):
    queue = DurableQueue(str(tmp_path / "q"))
    queue.submit(QueuedTask(task_id="x", tenant="a", gang=GangSpec("v4-8")))
    with pytest.raises(ValueError, match="duplicate"):
        queue.submit(QueuedTask(task_id="x", tenant="a",
                                gang=GangSpec("v4-8")))


# -- CLI -----------------------------------------------------------------------


def test_cli_sched_status_reads_durable_state(tmp_path, capsys):
    remote = str(tmp_path / "sched")
    pool = CapacityPool([32])
    quotas = {"prod": TenantQuota(chips=24, weight=2.0),
              "batch": TenantQuota(chips=16, weight=1.0)}
    scheduler, driver, now = make_sched(pool, quotas, remote=remote)
    scheduler.submit("prod", "v4-16", work=50.0, task_id="p0")
    scheduler.submit("batch", "v4-8", work=50.0, task_id="b0")
    scheduler.submit("batch", "v4-32", work=50.0, task_id="b1")  # won't fit quota
    scheduler.tick()
    assert cli_main(["sched", "status", "--remote", remote]) == 0
    out = capsys.readouterr().out
    lines = out.strip().splitlines()
    assert lines[0].split() == [
        "TENANT", "KIND", "QUEUED", "RUNNING", "CHIPS", "QUOTA", "SHARE",
        "DEFICIT", "REQUEUES", "QLAT-P50", "QLAT-P99", "DONE", "FAILED"]
    rows = {line.split()[0]: line.split() for line in lines[1:-1]}
    assert rows["prod"][1] == "batch"
    assert rows["prod"][3] == "1"        # running gangs
    assert rows["prod"][5] == "24"       # quota chips
    assert rows["batch"][2] == "1"       # b1 still queued
    assert rows["prod"][9].endswith("s")  # queue-latency p50 (placed gang)
    assert "pool:" in lines[-1]


def test_cli_sched_status_without_state(tmp_path, capsys):
    assert cli_main(["sched", "status", "--remote",
                     str(tmp_path / "empty")]) == 1
    assert "no scheduler state" in capsys.readouterr().out


# -- real tasks: scheduler preemption rides the PR 3 governor ------------------

STEPS = 16
RESUME_SCRIPT = f"""#!/bin/bash
ckpt="checkpoint-$TPU_TASK_NODE"
steps="steps-$TPU_TASK_NODE.log"
step=0
test -f "$ckpt" && step=$(cat "$ckpt")
while [ "$step" -lt {STEPS} ]; do
  step=$((step+1))
  echo "$step" > "$ckpt"
  echo "step-$step" >> "$steps"
  echo "step-$step"
  sleep 0.25
done
echo "done-$TPU_TASK_NODE"
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_scheduler_preemption_is_cloud_preemption_to_the_task(tmp_path,
                                                             monkeypatch):
    """End to end on REAL fake-mode tasks: the scheduler evicts a running
    gang for a higher-priority one through the control plane's graceful
    reclaim — to the victim's agents this is a cloud spot preemption
    (SIGTERM → final sync → SUSPENDED) — and when capacity frees, recovery
    rides the victim's own PR 3 requeue governor: checkpoint resume, step
    monotonicity, durable `recover` event. Nothing scheduler-specific
    exists on the task side; that is the tentpole's reuse contract."""
    import time as time_module

    from tpu_task import task as task_factory
    from tpu_task.common.cloud import Cloud, Provider
    from tpu_task.common.identifier import Identifier
    from tpu_task.common.values import (
        SPOT_ENABLED, Environment, Size, Task as TaskSpec,
    )

    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_HEARTBEAT_PERIOD", "0.2")
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "0")  # liveness off
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.2")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_CAP", "1.0")
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "10")
    cloud = Cloud(provider=Provider.TPU, region="us-central2")
    backends = {}

    def factory(task):
        backend = task_factory.new(
            cloud, Identifier.deterministic(task.task_id),
            TaskSpec(size=Size(machine=task.gang.accelerator),
                     environment=Environment(script=RESUME_SCRIPT),
                     spot=SPOT_ENABLED))
        backends[task.task_id] = backend
        return backend

    driver = TpuTaskDriver(factory, delete_on_release=False)
    pool = CapacityPool([4])                  # one v4-8 gang at a time
    quotas = {"lab": TenantQuota(chips=4, weight=1.0),
              "prod": TenantQuota(chips=4, weight=1.0)}
    scheduler = GangScheduler(pool, quotas, driver)
    try:
        victim = scheduler.submit("lab", "v4-8", priority=0,
                                  task_id="sched-victim")
        scheduler.tick()
        assert victim.state == "placed"
        node = backends["sched-victim"]._qr_name(0)

        def victim_steps():
            path = os.path.join(backends["sched-victim"]._bucket_dir,
                                "data", f"steps-{node}.log")
            try:
                return [int(line.split("-", 1)[1])
                        for line in open(path).read().split()
                        if line.startswith("step-")]
            except OSError:
                return []

        deadline = time_module.time() + 60
        while time_module.time() < deadline and len(victim_steps()) < 2:
            scheduler.tick()
            time_module.sleep(0.2)
        assert len(victim_steps()) >= 2, "victim never made durable progress"

        vip = scheduler.submit("prod", "v4-8", priority=2,
                               task_id="sched-vip")
        scheduler.tick()
        assert victim.state == "preempted"    # evicted through the plane
        assert vip.state == "placed"
        assert victim.attempts == 0           # policy, not failure

        deadline = time_module.time() + 120
        while time_module.time() < deadline and not scheduler.idle():
            scheduler.tick()
            time_module.sleep(0.2)
        assert scheduler.idle(), {
            task.task_id: task.state
            for task in scheduler.queue.tasks.values()}
        assert victim.state == "succeeded"
        assert vip.state == "succeeded"

        # Step monotonicity: the victim RESUMED from its checkpoint — the
        # graceful SIGTERM final-synced it — never restarted from scratch.
        steps = victim_steps()
        assert steps and steps[-1] == STEPS
        assert steps.count(1) == 1, "victim restarted from scratch"
        assert all(b >= a for a, b in zip(steps, steps[1:])), steps

        # The recovery is the PR 3 governor's own, durably recorded: a
        # fresh observer sees the `recover` event in the victim's mailbox.
        observer = task_factory.new(
            cloud, Identifier.deterministic("sched-victim"), TaskSpec())
        codes = [event.code for event in observer.events()]
        assert "recover" in codes, codes
    finally:
        for backend in backends.values():
            backend.delete()


LOCAL_STEPS = 10
LOCAL_RESUME_SCRIPT = f"""#!/bin/bash
step=0
test -f checkpoint && step=$(cat checkpoint)
while [ "$step" -lt {LOCAL_STEPS} ]; do
  step=$((step+1))
  echo "$step" > checkpoint
  echo "step-$step" >> steps.log
  echo "step-$step"
  sleep 0.2
done
echo local-done
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_scheduler_drives_local_machine_groups(tmp_path, monkeypatch):
    """Same scheduler, other backend: gangs as local ``MachineGroup``
    subprocess VMs. Eviction rides the group's graceful per-worker
    preemption (SIGTERM notice → final sync), recovery is the group's own
    reconcile-respawn with bucket restore — parked while evicted because
    the scheduler only polls gangs holding a reservation."""
    import time as time_module

    from tpu_task import task as task_factory
    from tpu_task.common.cloud import Cloud, Provider
    from tpu_task.common.identifier import Identifier
    from tpu_task.common.values import Environment, Task as TaskSpec

    monkeypatch.setenv("TPU_TASK_LOCAL_ROOT", str(tmp_path / "control-plane"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    cloud = Cloud(provider=Provider.LOCAL)
    backends = {}

    def factory(task):
        spec = TaskSpec(environment=Environment(script=LOCAL_RESUME_SCRIPT),
                        parallelism=task.gang.slices)
        backend = task_factory.new(
            cloud, Identifier.deterministic(task.task_id), spec)
        backends[task.task_id] = backend
        return backend

    driver = TpuTaskDriver(factory, delete_on_release=False)
    pool = CapacityPool([4])
    quotas = {"lab": TenantQuota(chips=4, weight=1.0),
              "prod": TenantQuota(chips=4, weight=1.0)}
    scheduler = GangScheduler(pool, quotas, driver)
    try:
        victim = scheduler.submit("lab", "v4-8", priority=0,
                                  task_id="local-victim")
        scheduler.tick()
        assert victim.state == "placed"

        def victim_steps():
            path = os.path.join(backends["local-victim"].group.bucket,
                                "data", "steps.log")
            try:
                return [int(line.split("-", 1)[1])
                        for line in open(path).read().split()
                        if line.startswith("step-")]
            except OSError:
                return []

        deadline = time_module.time() + 60
        while time_module.time() < deadline and len(victim_steps()) < 2:
            scheduler.tick()
            time_module.sleep(0.2)
        assert len(victim_steps()) >= 2, "victim never made durable progress"

        vip = scheduler.submit("prod", "v4-8", priority=2,
                               task_id="local-vip")
        scheduler.tick()
        assert victim.state == "preempted"
        assert vip.state == "placed"
        # Evicted means DOWN, not respawning: the group reconciles only
        # when polled, and preempted gangs aren't.
        time_module.sleep(1.0)
        assert backends["local-victim"].group.live_workers() == []

        deadline = time_module.time() + 120
        while time_module.time() < deadline and not scheduler.idle():
            scheduler.tick()
            time_module.sleep(0.2)
        assert scheduler.idle(), {
            task.task_id: task.state
            for task in scheduler.queue.tasks.values()}
        assert victim.state == "succeeded" and vip.state == "succeeded"

        steps = victim_steps()
        assert steps and steps[-1] == LOCAL_STEPS
        assert steps.count(1) == 1, "victim restarted from scratch"
        assert all(b >= a for a, b in zip(steps, steps[1:])), steps
        # The graceful eviction left the group's preempt event on record.
        codes = [event["code"]
                 for event in backends["local-victim"].group.events()]
        assert "preempt" in codes
    finally:
        for backend in backends.values():
            backend.delete()


# -- bench smoke (tier-1 perf contract) ---------------------------------------


@pytest.mark.perf
def test_bench_scheduler_small_poisson_zero_violations():
    """A small Poisson workload schedules end to end with zero invariant
    violations — the tier-1 canary for the `bench.py scheduler` section."""
    result = bench.bench_scheduler(n_tasks=60, seed=11, waves=1)
    assert result["invariant_violations"] == 0
    assert result["nonterminal"] == 0
    assert result["succeeded"] + result["failed"] == 60
    assert result["succeeded"] >= 55          # waves may exhaust a budget
    assert result["utilization_mean"] > 0.1
    assert result["queue_latency_p99_s"] >= result["queue_latency_p50_s"]
    # Replayable: the same seed reproduces the same virtual history.
    again = bench.bench_scheduler(n_tasks=60, seed=11, waves=1)
    assert again["virtual_makespan_s"] == result["virtual_makespan_s"]
    assert again["requeues_by_tenant"] == result["requeues_by_tenant"]
