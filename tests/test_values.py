"""Task value model: Variables.enrich glob semantics, status codes, spot policy."""

import os

from tpu_task.common.values import (
    SPOT_DISABLED,
    SPOT_ENABLED,
    Spot,
    StatusCode,
    Task,
    Variables,
)


def test_enrich_literal_values():
    variables = Variables({"FOO": "bar", "BAZ": "qux"})
    assert variables.enrich() == {"FOO": "bar", "BAZ": "qux"}


def test_enrich_resolves_none_from_environ(monkeypatch):
    monkeypatch.setenv("TPU_TASK_TEST_VAR", "hello")
    variables = Variables({"TPU_TASK_TEST_VAR": None})
    assert variables.enrich() == {"TPU_TASK_TEST_VAR": "hello"}


def test_enrich_glob_keys(monkeypatch):
    monkeypatch.setenv("MYPREFIX_ONE", "1")
    monkeypatch.setenv("MYPREFIX_TWO", "2")
    monkeypatch.setenv("OTHER_VAR", "3")
    variables = Variables({"MYPREFIX_*": None})
    enriched = variables.enrich()
    assert enriched == {"MYPREFIX_ONE": "1", "MYPREFIX_TWO": "2"}


def test_enrich_missing_env_is_empty():
    variables = Variables({"DEFINITELY_NOT_SET_ANYWHERE_12345": None})
    assert variables.enrich() == {}


def test_spot_policy():
    assert SPOT_DISABLED < 0
    assert SPOT_ENABLED == 0
    assert Spot(1.5) > 0


def test_status_codes():
    assert StatusCode.ACTIVE.value == "running"
    assert StatusCode.SUCCEEDED.value == "succeeded"
    assert StatusCode.FAILED.value == "failed"


def test_task_defaults():
    task = Task()
    assert task.parallelism == 1
    assert task.spot == SPOT_DISABLED
    assert task.environment.timeout.total_seconds() == 24 * 3600
