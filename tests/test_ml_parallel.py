"""Sharding + ring attention tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from tpu_task.ml import train
from tpu_task.ml.models import transformer
from tpu_task.ml.ops.attention import mha_reference
from tpu_task.ml.parallel import mesh as meshlib
from tpu_task.ml.parallel import sharding
from tpu_task.ml.parallel.ring_attention import ring_attention

TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32,
)


def test_balanced_mesh_shape():
    assert meshlib.balanced_mesh_shape(8, 3) == (2, 2, 2)
    assert meshlib.balanced_mesh_shape(1, 3) == (1, 1, 1)
    assert meshlib.balanced_mesh_shape(4, 2) == (2, 2)
    assert meshlib.balanced_mesh_shape(12, 3) == (3, 2, 2)


def test_make_mesh_axes():
    m = meshlib.make_mesh(8)
    assert m.axis_names == ("dp", "fsdp", "tp")
    assert m.devices.size == 8


def test_logical_rules_drop_missing_axes():
    m = meshlib.make_mesh(8, axis_names=("dp", "tp"), axis_sizes=(4, 2))
    spec = sharding.logical_to_mesh_axes(("embed", "heads"), mesh=m)
    # fsdp absent from this mesh → embed replicated; heads → tp.
    assert spec == PartitionSpec(None, "tp")
    batch = sharding.logical_to_mesh_axes(("batch", "seq"), mesh=m)
    assert batch == PartitionSpec(("dp",), None)


# -- partition registry: rule resolution + the compile seam -------------------


def test_match_partition_rules_regex_over_paths():
    """Regex rules resolve a tree WITHOUT logical annotations (the paged
    pools' case): "/"-joined paths, first match wins, logical targets go
    through the same table as annotations."""
    tree = [{"k": jnp.zeros((8, 4, 4, 2)), "v": jnp.zeros((8, 4, 4, 2))}
            for _ in range(2)]
    mesh = meshlib.make_mesh(8, axis_names=("tp",), axis_sizes=(8,))
    specs = sharding.match_partition_rules(
        ((r"(^|/)[kv]$", (None, None, "heads", None)),), tree, mesh=mesh)
    for layer in specs:
        assert layer["k"] == PartitionSpec(None, None, "tp", None)
        assert layer["v"] == PartitionSpec(None, None, "tp", None)


def test_match_partition_rules_logical_annotation_beats_regex():
    """A logical-axis annotation wins over a regex that also matches — the
    annotation sits next to the parameter definition and is the model's
    source of truth; regex covers the unannotated rest."""
    mesh = meshlib.make_mesh(8)
    tree = {"wq": jnp.zeros((8, 8)), "wz": jnp.zeros((8, 8))}
    specs = sharding.match_partition_rules(
        ((r"^w", ("mlp", None)),), tree, mesh=mesh,
        logical_axes={"wq": ("embed", "heads"), "wz": None})
    assert specs["wq"] == PartitionSpec("fsdp", "tp")   # annotation
    assert specs["wz"] == PartitionSpec("tp", None)     # regex fallback


def test_match_partition_rules_scalars_replicate():
    """Scalar / single-element leaves (optimizer counts, schedules) never
    partition, whatever the rules say."""
    tree = {"count": jnp.zeros(()), "one": jnp.zeros((1,)),
            "big": jnp.zeros((8, 8))}
    specs = sharding.match_partition_rules(
        ((r".", ("embed", "heads")),), tree,
        mesh=meshlib.make_mesh(8))
    assert specs["count"] == PartitionSpec()
    assert specs["one"] == PartitionSpec()
    assert specs["big"] == PartitionSpec("fsdp", "tp")


def test_match_partition_rules_unmatched_raises_with_path():
    """An unmatched parameter fails LOUDLY, naming its tree path — silent
    replication of a new 10B-param tensor is the failure mode this guards."""
    tree = {"layers": [{"mystery": jnp.zeros((4, 4))}]}
    with pytest.raises(ValueError, match=r"layers/0/mystery"):
        sharding.match_partition_rules(
            ((r"(^|/)wq$", ("embed", "heads")),), tree)


def test_match_partition_rules_drops_missing_mesh_axes():
    """Mesh axes absent from the target mesh drop to None — one rules
    table serves every mesh shape, for raw-PartitionSpec targets too."""
    mesh = meshlib.make_mesh(8, axis_names=("dp", "tp"), axis_sizes=(4, 2))
    tree = {"a": jnp.zeros((4, 4)), "b": jnp.zeros((4, 4))}
    specs = sharding.match_partition_rules(
        ((r"^a$", ("embed", "heads")),          # embed→fsdp: not in mesh
         (r"^b$", PartitionSpec("pp", "tp"))),  # raw spec, pp not in mesh
        tree, mesh=mesh)
    assert specs["a"] == PartitionSpec(None, "tp")
    assert specs["b"] == PartitionSpec(None, "tp")


def test_compile_step_modes_agree_with_eager():
    """The one compile seam: no-mesh plans are plain jit, jit-mode plans
    pin shardings, shard_map-mode plans run per shard — all three compute
    the same numbers for a collective-free fn."""
    mesh = meshlib.make_mesh(8, axis_names=("tp",), axis_sizes=(8,))
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)

    def fn(x):
        return x * 2.0 + 1.0

    ref = fn(x)
    plain = sharding.compile_step(fn, sharding.PartitionPlan())(x)
    spec = PartitionSpec("tp", None)
    jitted = sharding.compile_step(fn, sharding.PartitionPlan(
        mesh=mesh, in_specs=(spec,), out_specs=spec))(x)
    mapped = sharding.compile_step(fn, sharding.PartitionPlan(
        mesh=mesh, mode="shard_map", in_specs=(spec,), out_specs=spec))(x)
    for out in (plain, jitted, mapped):
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert jitted.sharding.spec == spec
    with pytest.raises(ValueError, match="mode"):
        sharding.PartitionPlan(mode="pmap")


def test_gqa_shard_map_core_bit_exact_per_slice():
    """The gqa core under shard_map (kv heads over tp) is bit-exact against
    running the core on each head slice separately — no cross-shard
    reduction exists, so sharding cannot change a bit. (vs the MONOLITHIC
    full-width program it is tolerance-only: XLA schedules the fused
    einsum differently — the documented split in docs/parity.md.)"""
    from tpu_task.ml.ops.attention import (
        gqa_cached_attention,
        gqa_cached_attention_tp,
    )

    mesh = meshlib.make_mesh(8, axis_names=("tp",), axis_sizes=(8,))
    rng = np.random.default_rng(3)
    b, s, h, kv, L, d = 2, 1, 8, 8, 16, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((b, L, kv, d)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((b, L, kv, d)), jnp.float32)
    pos = jnp.asarray([[5], [9]])
    out = np.asarray(gqa_cached_attention_tp(q, kc, vc, pos, mesh))
    hs, kvs = h // 8, kv // 8
    jit_core = jax.jit(gqa_cached_attention)   # compiled, like the shards
    per_slice = np.concatenate([
        np.asarray(jit_core(
            q[:, :, i * hs:(i + 1) * hs], kc[:, :, i * kvs:(i + 1) * kvs],
            vc[:, :, i * kvs:(i + 1) * kvs], pos))
        for i in range(8)], axis=2)
    assert (out == per_slice).all()
    np.testing.assert_allclose(
        out, np.asarray(gqa_cached_attention(q, kc, vc, pos)), atol=1e-6)
    with pytest.raises(ValueError, match="kv_heads"):
        gqa_cached_attention_tp(q, kc[:, :, :6], vc[:, :, :6], pos, mesh)


def test_sharded_train_step_matches_single_device():
    """The dp/fsdp/tp-sharded step computes the same numbers as 1 device."""
    mesh = meshlib.make_mesh(8)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, TINY.vocab_size)

    ref_state = train.init_state(jax.random.PRNGKey(0), TINY)
    ref_step = train.make_train_step(TINY, donate=False)
    ref_state, ref_metrics = ref_step(ref_state, tokens)

    state = train.init_state(jax.random.PRNGKey(0), TINY)
    state, specs = train.shard_state(state, TINY, mesh)
    step = train.make_train_step(TINY, mesh=mesh, donate=False)(state)
    state, metrics = step(state, tokens)

    assert np.allclose(float(metrics["loss"]), float(ref_metrics["loss"]), atol=1e-4)
    for a, b in zip(jax.tree.leaves(ref_state.params), jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    # params actually sharded: embed is (vocab=tp, embed=fsdp)
    embed_sharding = state.params["embed"].sharding
    assert embed_sharding.spec == PartitionSpec("tp", "fsdp")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = meshlib.make_mesh(8, axis_names=("sp",), axis_sizes=(8,))
    b, s, h, d = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    ref = mha_reference(q, k, v, causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ring_attention_kernel_impl_matches_dense(impl):
    """Ring with the Pallas block kernel (interpret on CPU) stays exact."""
    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, d = 2, 64, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)
    ref = mha_reference(q, k, v, True)
    out = ring_attention(q, k, v, mesh, causal=True, impl=impl, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ring_attention_gradients_match_dense(causal, impl):
    """The ring's custom VJP (circulating dk/dv) equals dense autodiff."""
    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, d = 2, 32, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)

    def f_ref(q, k, v):
        return (mha_reference(q, k, v, causal) ** 2).sum()

    def f_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=causal, impl=impl,
                               interpret=True) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


def test_state_pspecs_distinguish_same_shaped_params():
    """wq (embed,heads)→(fsdp,tp) and wo (heads,embed)→(tp,fsdp) are both
    square — the optimizer moments must follow each param's own layout."""
    mesh = meshlib.make_mesh(8)
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_head=8, d_ff=64,
        dtype=jnp.float32,
    )
    state = train.init_state(jax.random.PRNGKey(0), cfg)
    specs = train.state_pspecs(state, cfg, mesh)
    layer_p = specs.params["layers"][0]
    assert layer_p["wq"] == PartitionSpec("fsdp", "tp")
    assert layer_p["wo"] == PartitionSpec("tp", "fsdp")
    # find the adam moments inside the optax chain state
    found = []

    def visit(path, leaf):
        keys = tuple(str(k) for k in path)
        if keys[-3:] == ("['layers']", "[0]", "['wq']"):
            found.append(("wq", leaf))
        if keys[-3:] == ("['layers']", "[0]", "['wo']"):
            found.append(("wo", leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, specs.opt_state)
    assert found, "no adam moments matched the param paths"
    for name, spec in found:
        want = PartitionSpec("fsdp", "tp") if name == "wq" else PartitionSpec("tp", "fsdp")
        assert spec == want, f"{name}: {spec} != {want}"


def test_distributed_init_from_env_noop():
    assert meshlib.distributed_init_from_env({}) is False
    assert meshlib.distributed_init_from_env({"TPU_TASK_NUM_WORKERS": "1"}) is False


def test_worker_env_contract():
    env = meshlib.worker_env(2, 4, "10.0.0.2:8476")
    assert env == {
        "TPU_TASK_WORKER_ID": "2",
        "TPU_TASK_NUM_WORKERS": "4",
        "TPU_TASK_COORDINATOR": "10.0.0.2:8476",
    }


# -- zigzag (balanced causal) ring attention ----------------------------------


def test_zigzag_permute_roundtrip():
    from tpu_task.ml.parallel.ring_attention import (
        zigzag_permute, zigzag_unpermute,
    )

    x = jnp.arange(2 * 32 * 3, dtype=jnp.float32).reshape(2, 32, 3)
    z = zigzag_permute(x, devices=4)
    np.testing.assert_array_equal(np.asarray(zigzag_unpermute(z, 4)),
                                  np.asarray(x))
    # Device 0's contiguous shard holds stripes 0 and 2P-1 = 7.
    stripe = 32 // 8
    np.testing.assert_array_equal(np.asarray(z[:, :stripe]),
                                  np.asarray(x[:, :stripe]))
    np.testing.assert_array_equal(np.asarray(z[:, stripe:2 * stripe]),
                                  np.asarray(x[:, 7 * stripe:]))


def test_zigzag_ring_attention_matches_dense():
    from tpu_task.ml.parallel.ring_attention import zigzag_ring_attention

    mesh = meshlib.make_mesh(8, axis_names=("sp",), axis_sizes=(8,))
    b, s, h, d = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)
    ref = mha_reference(q, k, v, True)
    out = zigzag_ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_zigzag_ring_attention_gradients_match_dense(impl):
    """The balanced schedule's custom VJP equals dense causal autodiff."""
    from tpu_task.ml.parallel.ring_attention import zigzag_ring_attention

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, d = 2, 32, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)

    def f_ref(q, k, v):
        return (mha_reference(q, k, v, True) ** 2).sum()

    def f_zz(q, k, v):
        return (zigzag_ring_attention(q, k, v, mesh, impl=impl,
                                      interpret=True) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_zz = jax.grad(f_zz, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_zz, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


def test_zigzag_single_device_degenerates_to_causal():
    from tpu_task.ml.parallel.ring_attention import zigzag_ring_attention

    mesh = meshlib.make_mesh(1, axis_names=("sp",), axis_sizes=(1,))
    b, s, h, d = 1, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)
    ref = mha_reference(q, k, v, True)
    out = zigzag_ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# -- process-sharded checkpoints ----------------------------------------------


def test_sharded_checkpoint_roundtrip_preserves_shardings(tmp_path):
    """Sharded save/restore on the 8-device mesh: values equal, shardings
    preserved, shard entries keyed by GLOBAL index ranges (restore survives
    process renumbering by construction)."""
    from tpu_task.ml import (
        restore_checkpoint_sharded, save_checkpoint_sharded, train,
    )

    mesh = meshlib.make_mesh(8)
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    state, _ = train.shard_state(state, TINY, mesh)

    save_checkpoint_sharded(tmp_path, 7, state.params)
    files = list(tmp_path.glob("ckpt-7.shard-*.npz"))
    assert len(files) == 1  # single-process test: one shard file

    # Fresh template: same shardings, different values (PRNGKey(1)).
    template, _ = train.shard_state(
        train.init_state(jax.random.PRNGKey(1), TINY), TINY, mesh)
    restored = restore_checkpoint_sharded(tmp_path, template.params)
    for original, back in zip(jax.tree.leaves(state.params),
                              jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(original), np.asarray(back),
                                   atol=0)
        assert back.sharding == original.sharding


def test_sharded_checkpoint_detects_missing_shards(tmp_path):
    from tpu_task.ml import (
        restore_checkpoint_sharded, save_checkpoint_sharded, train,
    )

    mesh = meshlib.make_mesh(8)
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    state, _ = train.shard_state(state, TINY, mesh)
    path = save_checkpoint_sharded(tmp_path, 3, state.params)

    # Corrupt: drop some entries (simulates a missing worker's shard file).
    import numpy as _np

    with _np.load(path) as payload:
        keys = payload.files
        kept = {k: payload[k] for k in keys[: len(keys) // 2]}
    path.unlink()
    _np.savez(tmp_path / "ckpt-3.shard-0.npz", **kept)
    with pytest.raises(FileNotFoundError, match="shard"):
        restore_checkpoint_sharded(tmp_path, state.params)


def test_sharded_restore_falls_back_past_partial_newest_step(tmp_path):
    """Workers upload shards on independent loops, so the newest step can be
    partial after a preemption — restore must fall back to the last COMPLETE
    step, not crash (the whole point of checkpointing)."""
    import numpy as _np

    from tpu_task.ml import (
        restore_checkpoint_sharded, save_checkpoint_sharded, train,
    )

    mesh = meshlib.make_mesh(8)
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    state, _ = train.shard_state(state, TINY, mesh)
    save_checkpoint_sharded(tmp_path, 9, state.params)  # complete

    newer = save_checkpoint_sharded(tmp_path, 10, state.params)
    with _np.load(newer) as payload:  # truncate step 10 → partial
        keys = payload.files
        kept = {k: payload[k] for k in keys[: len(keys) // 2]}
    newer.unlink()
    _np.savez(tmp_path / "ckpt-10.shard-0.npz", **kept)

    restored = restore_checkpoint_sharded(tmp_path, state.params)
    for original, back in zip(jax.tree.leaves(state.params),
                              jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(original), np.asarray(back),
                                   atol=0)


def test_sharded_restore_survives_topology_change(tmp_path):
    """An older COMPLETE checkpoint saved under a different process count
    must still restore during fallback: each step is judged by its OWN
    save-time topology (per-step manifest), not the newest pointer's."""
    import json as _json
    import shutil as _shutil

    from tpu_task.ml import (
        restore_checkpoint_sharded, save_checkpoint_sharded, train,
    )

    mesh = meshlib.make_mesh(8)
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    state, _ = train.shard_state(state, TINY, mesh)
    complete = save_checkpoint_sharded(tmp_path, 5, state.params)

    # Fake a newer step saved by a 2-process job whose shard-1 upload never
    # landed: 1/2 shard files, manifest + pointer claim process_count=2.
    _shutil.copy(complete, tmp_path / "ckpt-6.shard-0.npz")
    (tmp_path / "ckpt-6.meta").write_text(
        _json.dumps({"step": 6, "process_count": 2}))
    (tmp_path / "LATEST_SHARDED").write_text(
        _json.dumps({"step": 6, "file": "ckpt-6.shard-0.npz",
                     "process_count": 2}))

    restored = restore_checkpoint_sharded(tmp_path, state.params)
    for original, back in zip(jax.tree.leaves(state.params),
                              jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(original), np.asarray(back),
                                   atol=0)


def test_resave_after_topology_shrink_reaps_stale_shards(tmp_path):
    """Re-saving a step under a smaller process count must remove the old
    topology's higher-index shard files, or the completeness check
    (indices == 0..expected-1) would reject the step forever."""
    from tpu_task.ml import (
        restore_checkpoint_sharded, save_checkpoint_sharded, train,
    )

    mesh = meshlib.make_mesh(8)
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    state, _ = train.shard_state(state, TINY, mesh)
    # Leftover from a previous 6-process save of the same step.
    (tmp_path / "ckpt-6.shard-5.npz").write_bytes(b"stale")

    save_checkpoint_sharded(tmp_path, 6, state.params)
    assert not (tmp_path / "ckpt-6.shard-5.npz").exists()
    restored = restore_checkpoint_sharded(tmp_path, state.params)
    for original, back in zip(jax.tree.leaves(state.params),
                              jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(original), np.asarray(back),
                                   atol=0)


def test_sharded_restore_accepts_legacy_steps_without_manifest(tmp_path):
    """Checkpoints saved before the per-step manifest existed carry only
    shard files; they are judged by the CURRENT topology's process count
    (never by whatever files happen to be present, which would bless
    truncated prefixes)."""
    from tpu_task.ml import (
        restore_checkpoint_sharded, save_checkpoint_sharded, train,
    )

    mesh = meshlib.make_mesh(8)
    state = train.init_state(jax.random.PRNGKey(0), TINY)
    state, _ = train.shard_state(state, TINY, mesh)
    save_checkpoint_sharded(tmp_path, 2, state.params)
    (tmp_path / "ckpt-2.meta").unlink()
    (tmp_path / "LATEST_SHARDED").unlink()

    restored = restore_checkpoint_sharded(tmp_path, state.params)
    for original, back in zip(jax.tree.leaves(state.params),
                              jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(original), np.asarray(back),
                                   atol=0)


def test_sp_train_step_matches_replicated_step():
    """The sequence-parallel train step (zigzag ring attention + seq-sharded
    activations over sp) produces the same loss and updated params as the
    plain replicated step — sequence parallelism must be a layout choice,
    not a numerics choice."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
        dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)

    plain_state = train.init_state(jax.random.PRNGKey(0), cfg)
    plain_step = train.make_train_step(cfg, donate=False)
    plain_state, plain_metrics = plain_step(plain_state, tokens)

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    sp_state = train.init_state(jax.random.PRNGKey(0), cfg)
    sp_state, _ = train.shard_state(sp_state, cfg, mesh)
    sp_step = train.make_sp_train_step(cfg, mesh, donate=False)(sp_state)
    sp_state, sp_metrics = sp_step(sp_state, tokens)

    assert abs(float(sp_metrics["loss"]) - float(plain_metrics["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(sp_state.params),
                    jax.tree.leaves(plain_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_sp_train_step_with_dp_axis():
    """dp × sp combined mesh: batch shards over dp, seq over sp, one step
    runs and the loss is finite (collective wiring check)."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=8, d_ff=64,
        dtype=jnp.float32)
    mesh = meshlib.make_mesh(8, axis_names=("dp", "sp"), axis_sizes=(2, 4))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                                cfg.vocab_size)
    state = train.init_state(jax.random.PRNGKey(0), cfg)
    state, _ = train.shard_state(state, cfg, mesh)
    step = train.make_sp_train_step(cfg, mesh, donate=False)(state)
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))


def test_sp_train_step_with_fsdp_axis():
    """fsdp × sp mesh: the batch placement comes from the logical rules, so
    fsdp (not just dp) shards the batch consistently across the activation
    constraint, the ring's shard_map, and the token input sharding."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=8, d_ff=64,
        dtype=jnp.float32)
    mesh = meshlib.make_mesh(8, axis_names=("fsdp", "sp"), axis_sizes=(2, 4))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                                cfg.vocab_size)
    state = train.init_state(jax.random.PRNGKey(0), cfg)
    state, _ = train.shard_state(state, cfg, mesh)
    step = train.make_sp_train_step(cfg, mesh, donate=False)(state)
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))


# -- ulysses (all-to-all) context parallelism ---------------------------------


def test_ulysses_attention_matches_dense():
    from tpu_task.ml.parallel.ulysses import ulysses_attention

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, d = 2, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)
    for causal in (True, False):
        out = ulysses_attention(q, k, v, mesh, causal=causal)
        ref = mha_reference(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


def test_ulysses_attention_gradients_match_dense():
    """all_to_all transposes to its inverse, so plain autodiff through the
    resharded attention must equal dense causal autodiff."""
    from tpu_task.ml.parallel.ulysses import ulysses_attention

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, d = 1, 16, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d)) for kk in ks)

    def f_ref(q, k, v):
        return (mha_reference(q, k, v, True) ** 2).sum()

    def f_ul(q, k, v):
        return (ulysses_attention(q, k, v, mesh) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_ul = jax.grad(f_ul, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ul, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    from tpu_task.ml.parallel.ulysses import ulysses_attention

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    q = jnp.zeros((1, 16, 6, 8))  # 6 heads % 4 devices != 0
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, q, q, mesh)


def test_sp_train_step_ulysses_matches_replicated_step():
    """The ulysses-mode sp step equals the plain replicated step exactly —
    same contract as the zigzag mode."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
        dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)

    plain_state = train.init_state(jax.random.PRNGKey(0), cfg)
    plain_step = train.make_train_step(cfg, donate=False)
    plain_state, plain_metrics = plain_step(plain_state, tokens)

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    sp_state = train.init_state(jax.random.PRNGKey(0), cfg)
    sp_state, _ = train.shard_state(sp_state, cfg, mesh)
    sp_step = train.make_sp_train_step(
        cfg, mesh, donate=False, context_parallel="ulysses")(sp_state)
    sp_state, sp_metrics = sp_step(sp_state, tokens)

    assert abs(float(sp_metrics["loss"]) - float(plain_metrics["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(sp_state.params),
                    jax.tree.leaves(plain_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


# -- GQA across the sequence-parallel boundary (narrow-KV wire format) --------


def _gqa_cfg():
    from tpu_task.ml.models import transformer

    return transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
        dtype=jnp.float32, n_kv_heads=2)


def test_zigzag_ring_narrow_kv_matches_dense():
    """Narrow k/v into the ring == dense attention on pre-expanded k/v:
    the expansion moved inside the shard, the math did not."""
    from tpu_task.ml.models.transformer import expand_kv
    from tpu_task.ml.parallel.ring_attention import zigzag_ring_attention

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, kv, d = 2, 32, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, kv, d))
    v = jax.random.normal(ks[2], (b, s, kv, d))
    out = zigzag_ring_attention(q, k, v, mesh)
    ref = mha_reference(q, expand_kv(k, h), expand_kv(v, h), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_ring_narrow_kv_gradients_match_dense():
    from tpu_task.ml.models.transformer import expand_kv
    from tpu_task.ml.parallel.ring_attention import zigzag_ring_attention

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, kv, d = 1, 16, 4, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(12), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, kv, d))
    v = jax.random.normal(ks[2], (b, s, kv, d))

    def f_ref(q, k, v):
        return (mha_reference(q, expand_kv(k, h), expand_kv(v, h),
                              True) ** 2).sum()

    def f_ring(q, k, v):
        return (zigzag_ring_attention(q, k, v, mesh) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        assert a.shape == b_.shape  # dk/dv at NARROW width both sides
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)


def test_sp_gqa_zigzag_step_matches_replicated():
    """sp-GQA pin: the zigzag sp train step with narrow-KV wire format
    still equals the replicated GQA step exactly."""
    from tpu_task.ml import train

    cfg = _gqa_cfg()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)
    plain_state = train.init_state(jax.random.PRNGKey(0), cfg)
    plain_state, plain_metrics = train.make_train_step(
        cfg, donate=False)(plain_state, tokens)

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    sp_state = train.init_state(jax.random.PRNGKey(0), cfg)
    sp_state, _ = train.shard_state(sp_state, cfg, mesh)
    sp_step = train.make_sp_train_step(cfg, mesh, donate=False)(sp_state)
    sp_state, sp_metrics = sp_step(sp_state, tokens)

    assert abs(float(sp_metrics["loss"]) - float(plain_metrics["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(sp_state.params),
                    jax.tree.leaves(plain_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_sp_gqa_ulysses_step_matches_replicated():
    """Ulysses with kv_heads % sp == 0: narrow a2a path, exact equality.
    n_kv_heads=2 over sp=2."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
        dtype=jnp.float32, n_kv_heads=2)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)
    plain_state = train.init_state(jax.random.PRNGKey(0), cfg)
    plain_state, plain_metrics = train.make_train_step(
        cfg, donate=False)(plain_state, tokens)

    mesh = meshlib.make_mesh(2, axis_names=("sp",), axis_sizes=(2,))
    sp_state = train.init_state(jax.random.PRNGKey(0), cfg)
    sp_state, _ = train.shard_state(sp_state, cfg, mesh)
    sp_step = train.make_sp_train_step(
        cfg, mesh, donate=False, context_parallel="ulysses")(sp_state)
    sp_state, sp_metrics = sp_step(sp_state, tokens)

    assert abs(float(sp_metrics["loss"]) - float(plain_metrics["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(sp_state.params),
                    jax.tree.leaves(plain_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_ulysses_gqa_widen_fallback_exact():
    """kv_heads % sp != 0 (2 kv heads over sp=4): Ulysses widens before the
    shard — collective saving forfeited, exactness kept."""
    from tpu_task.ml.models.transformer import expand_kv
    from tpu_task.ml.parallel.ulysses import ulysses_attention

    mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
    b, s, h, kv, d = 2, 32, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(13), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, kv, d))
    v = jax.random.normal(ks[2], (b, s, kv, d))
    out = ulysses_attention(q, k, v, mesh)
    ref = mha_reference(q, expand_kv(k, h), expand_kv(v, h), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def _collective_permute_bytes(hlo_text: str) -> int:
    """Total bytes moved by collective-permute ops in compiled HLO."""
    import re

    sizes = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f64": 8}
    total = 0
    for match in re.finditer(
            r"= \(?(\w+)\[([\d,]*)\][^)]*?\)? collective-permute", hlo_text):
        dtype, dims = match.groups()
        count = 1
        for dim in filter(None, dims.split(",")):
            count *= int(dim)
        total += count * sizes.get(dtype, 4)
    return total


def test_sp_gqa_narrow_wire_reduces_collective_bytes():
    """The measurable claim: with group factor 4 (n_kv_heads=1 vs MHA), the
    compiled sp train step moves LESS collective-permute traffic — k/v and
    dk/dv all circulate at KV width. Compares total collective-permute
    bytes parsed from the compiled HLO of both steps."""
    from tpu_task.ml import train
    from tpu_task.ml.models import transformer

    def step_bytes(n_kv_heads):
        cfg = transformer.TransformerConfig(
            vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_head=8,
            d_ff=64, dtype=jnp.float32, n_kv_heads=n_kv_heads)
        mesh = meshlib.make_mesh(4, axis_names=("sp",), axis_sizes=(4,))
        state = train.init_state(jax.random.PRNGKey(0), cfg)
        state, _ = train.shard_state(state, cfg, mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                    cfg.vocab_size)
        step = train.make_sp_train_step(cfg, mesh, donate=False)(state)
        text = step.lower(state, tokens).compile().as_text()
        return _collective_permute_bytes(text)

    mha = step_bytes(None)
    gqa = step_bytes(1)  # group factor 4
    assert mha > 0 and gqa > 0
    # k/v + dk/dv shrink 4x; other permuted tensors (dq handoffs in the
    # 1F1B-style ring bookkeeping, activation reshards) don't, so the
    # measured total lands near halved (observed 35864 vs 69656 bytes at
    # this toy shape — 1.94x). Assert a solid reduction without pinning
    # XLA fusion details.
    assert gqa < 0.6 * mha, (gqa, mha)
