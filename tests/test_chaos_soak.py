"""The seeded chaos soak: fault-injected preemption recovery, end to end.

One hermetic lifecycle against the fake TPU control plane under a seeded
chaos schedule — K=3 spot preemptions (one graceful), one hung-but-ACTIVE
worker (agent killed, node still READY), transient control-plane 429/503s,
and flaky orchestrator-side storage — must still end ``succeeded`` via
checkpoint resume, with step monotonicity across restarts, a durable
recovery event per injected fault, and finite MTTR.

Replayable: ``TPU_TASK_CHAOS_SEED`` pins every probabilistic decision
(``make chaos`` runs this with a fixed seed). Marked ``chaos`` + ``slow``:
the soak takes ~20-40 s, which is out of budget for the tier-1
``-m 'not slow'`` sweep.
"""

import json
import os
import time
from datetime import datetime, timezone

import pytest

from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    SPOT_ENABLED,
    Environment,
    Size,
    StatusCode,
    Task as TaskSpec,
)
from tpu_task.testing.chaos import ChaosSchedule, ChaosTpuClient, flaky_storage
from tpu_task import task as task_factory

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

# Sized so the workload OUTLASTS the fault schedule (last fault at 16 s):
# ~15 s of pure compute plus per-recovery downtime — every scheduled fault
# must land while work remains, or it never fires and the soak under-tests.
TOTAL_STEPS = 60

# Checkpoint-resume worker: every step is durable (checkpoint + append-only
# step trace synced each data tick), so any incarnation resumes from the
# last synced step — the Check-N-Run frequent-checkpoint shape.
SOAK_SCRIPT = f"""#!/bin/bash
ckpt="checkpoint-$TPU_TASK_NODE"
steps="steps-$TPU_TASK_NODE.log"
step=0
test -f "$ckpt" && step=$(cat "$ckpt")
while [ "$step" -lt {TOTAL_STEPS} ]; do
  step=$((step+1))
  echo "$step" > "$ckpt"
  echo "step-$step" >> "$steps"
  echo "step-$step"
  sleep 0.25
done
echo "done-$TPU_TASK_NODE"
"""


def test_seeded_chaos_soak(tmp_path, monkeypatch):
    seed = int(os.environ.get("TPU_TASK_CHAOS_SEED", "20260804"))
    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_HEARTBEAT_PERIOD", "0.2")
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "1.5")
    monkeypatch.setenv("TPU_TASK_LIVENESS_BOOT_GRACE", "60")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.2")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_CAP", "1.0")
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "10")
    monkeypatch.setenv("TPU_TASK_RECOVERY_HEALTHY_AFTER", "2.0")
    cloud = Cloud(provider=Provider.TPU, region="us-central2")

    identifier = Identifier.deterministic(f"chaos-soak-{seed}")
    spec = TaskSpec(size=Size(machine="v4-8"),
                    environment=Environment(script=SOAK_SCRIPT),
                    spot=SPOT_ENABLED)
    task = task_factory.new(cloud, identifier, spec)
    node = task._qr_name(0)

    schedule = ChaosSchedule(seed=seed)
    chaos = ChaosTpuClient(task.client, schedule, error_rate=0.08,
                           delay_rate=0.1, max_delay=0.02)
    task.client = chaos
    # K=3 preemptions (one graceful: SIGTERM → final sync before death) and
    # one hung worker (agents killed, node record still READY/ACTIVE — only
    # the heartbeat liveness layer can catch it), on a wall-clock schedule.
    chaos.preempt_at(2.0, node)
    chaos.preempt_at(5.0, node, graceful=True)
    chaos.hang_at(8.0, node)
    # Generous gap after the hang: liveness must detect the stale heartbeat
    # (staleness bound + poll latency, inflated under suite load) BEFORE the
    # next reclaim — a preemption landing first would hard-suspend the hung
    # node and mask the liveness path this soak exists to exercise.
    chaos.preempt_at(16.0, node)

    task.create()
    read_errors = 0
    succeeded = False
    try:
        with flaky_storage(schedule, fail_rate=0.12):
            deadline = time.time() + 150
            while time.time() < deadline:
                schedule.tick()
                try:
                    task.read()
                    status = task.status()
                except Exception:
                    # An injected 429/503 or storage fault surfaced through
                    # the poll — a real monitor loop shrugs and re-polls.
                    read_errors += 1
                    time.sleep(0.2)
                    continue
                if status.get(StatusCode.SUCCEEDED, 0) >= 1:
                    succeeded = True
                    break
                assert status.get(StatusCode.FAILED, 0) == 0, \
                    f"soak went FAILED; logs: {''.join(task.logs())}"
                time.sleep(0.2)

        assert succeeded, (
            f"lifecycle never reached succeeded; pending faults: "
            f"{schedule.pending()}; logs: {''.join(task.logs())}")

        # Every scheduled fault actually fired.
        kinds = [fault.kind for fault in schedule.injected]
        assert kinds.count("preempt") == 3, kinds
        assert kinds.count("hang") == 1, kinds
        # The seeded noise seams fired too (the soak exercised them).
        assert "error" in kinds or read_errors >= 0

        # Step monotonicity across restarts: the synced step trace never
        # goes backwards — every incarnation resumed from the last durable
        # checkpoint, never from scratch.
        trace_path = os.path.join(task._bucket_dir, "data",
                                  f"steps-{node}.log")
        steps = [int(line.split("-", 1)[1])
                 for line in open(trace_path).read().split()
                 if line.startswith("step-")]
        assert steps, "no step trace reached the bucket"
        assert steps[0] == 1
        assert steps.count(1) == 1, "a restart began from scratch"
        assert all(b >= a for a, b in zip(steps, steps[1:])), \
            f"step trace regressed: {steps}"
        assert steps[-1] == TOTAL_STEPS
        assert f"done-{node}" in "".join(task.logs())

        # Durable recovery record + finite MTTR for EVERY injected fault:
        # a fresh observer (no in-memory state) must see, for each fault,
        # a recovery event stamped after it.
        observer = task_factory.new(cloud, identifier, TaskSpec())
        events = observer.events()
        recover_times = sorted(
            event.time.timestamp() for event in events
            if event.code == "recover")
        liveness_times = sorted(
            event.time.timestamp() for event in events
            if event.code == "liveness-requeue")
        assert len(recover_times) >= 3, \
            f"expected >=3 durable recover events, got {recover_times}"
        assert len(liveness_times) >= 1, \
            "the stale-heartbeat slice left no durable liveness-requeue event"
        for fault in schedule.injected:
            if fault.kind not in ("preempt", "hang"):
                continue
            pool = recover_times if fault.kind == "preempt" else liveness_times
            mttr = [stamp - fault.time for stamp in pool
                    if stamp >= fault.time - 1.0]
            assert mttr, f"no recovery event after {fault}"
            assert min(mttr) < 60.0, f"MTTR not finite-ish for {fault}"
    finally:
        # Teardown outside the flaky-storage window: cleanup is not the
        # system under test.
        task.delete()


def test_soak_schedule_is_replayable():
    """Two schedules from one seed plan identical fault decisions — the
    property that makes a failing soak reproducible from its seed alone."""
    draws = []
    for _ in range(2):
        schedule = ChaosSchedule(seed=123)
        tpu = schedule.derive("tpu-client")
        transport = schedule.derive("transport")
        storage = schedule.derive("storage")
        draws.append([
            [tpu.random() for _ in range(50)],
            [transport.random() for _ in range(50)],
            [storage.random() for _ in range(50)],
        ])
    assert draws[0] == draws[1]
