"""The seeded chaos soak: fault-injected preemption recovery, end to end.

One hermetic lifecycle against the fake TPU control plane under a seeded
chaos schedule — K=3 spot preemptions (one graceful), one hung-but-ACTIVE
worker (agent killed, node still READY), transient control-plane 429/503s,
and flaky orchestrator-side storage — must still end ``succeeded`` via
checkpoint resume, with step monotonicity across restarts, a durable
recovery event per injected fault, and finite MTTR.

Replayable: ``TPU_TASK_CHAOS_SEED`` pins every probabilistic decision
(``make chaos`` runs this with a fixed seed). Marked ``chaos`` + ``slow``:
the soak takes ~20-40 s, which is out of budget for the tier-1
``-m 'not slow'`` sweep.
"""

import json
import os
import time
from datetime import datetime, timezone

import pytest

from tpu_task.common.cloud import Cloud, Provider
from tpu_task.common.identifier import Identifier
from tpu_task.common.values import (
    SPOT_ENABLED,
    Environment,
    Size,
    StatusCode,
    Task as TaskSpec,
)
from tpu_task.testing.chaos import (
    ChaosSchedule,
    ChaosTpuClient,
    flaky_storage,
    preemption_wave_at,
)
from tpu_task import task as task_factory

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

# Sized so the workload OUTLASTS the fault schedule (last fault at 16 s):
# ~15 s of pure compute plus per-recovery downtime — every scheduled fault
# must land while work remains, or it never fires and the soak under-tests.
TOTAL_STEPS = 60

# Checkpoint-resume worker: every step is durable (checkpoint + append-only
# step trace synced each data tick), so any incarnation resumes from the
# last synced step — the Check-N-Run frequent-checkpoint shape.
SOAK_SCRIPT = f"""#!/bin/bash
ckpt="checkpoint-$TPU_TASK_NODE"
steps="steps-$TPU_TASK_NODE.log"
step=0
test -f "$ckpt" && step=$(cat "$ckpt")
while [ "$step" -lt {TOTAL_STEPS} ]; do
  step=$((step+1))
  echo "$step" > "$ckpt"
  echo "step-$step" >> "$steps"
  echo "step-$step"
  sleep 0.25
done
echo "done-$TPU_TASK_NODE"
"""


def test_seeded_chaos_soak(tmp_path, monkeypatch):
    seed = int(os.environ.get("TPU_TASK_CHAOS_SEED", "20260804"))
    monkeypatch.setenv("TPU_TASK_FAKE_TPU_ROOT", str(tmp_path / "fake-tpu"))
    monkeypatch.setenv("TPU_TASK_LOCAL_LOG_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_DATA_PERIOD", "0.1")
    monkeypatch.setenv("TPU_TASK_LOCAL_HEARTBEAT_PERIOD", "0.2")
    monkeypatch.setenv("TPU_TASK_HEARTBEAT_STALE_AFTER", "1.5")
    monkeypatch.setenv("TPU_TASK_LIVENESS_BOOT_GRACE", "60")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.2")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_CAP", "1.0")
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "10")
    monkeypatch.setenv("TPU_TASK_RECOVERY_HEALTHY_AFTER", "2.0")
    cloud = Cloud(provider=Provider.TPU, region="us-central2")

    identifier = Identifier.deterministic(f"chaos-soak-{seed}")
    spec = TaskSpec(size=Size(machine="v4-8"),
                    environment=Environment(script=SOAK_SCRIPT),
                    spot=SPOT_ENABLED)
    task = task_factory.new(cloud, identifier, spec)
    node = task._qr_name(0)

    schedule = ChaosSchedule(seed=seed)
    chaos = ChaosTpuClient(task.client, schedule, error_rate=0.08,
                           delay_rate=0.1, max_delay=0.02)
    task.client = chaos
    # K=3 preemptions (one graceful: SIGTERM → final sync before death) and
    # one hung worker (agents killed, node record still READY/ACTIVE — only
    # the heartbeat liveness layer can catch it), on a wall-clock schedule.
    chaos.preempt_at(2.0, node)
    chaos.preempt_at(5.0, node, graceful=True)
    chaos.hang_at(8.0, node)
    # Generous gap after the hang: liveness must detect the stale heartbeat
    # (staleness bound + poll latency, inflated under suite load) BEFORE the
    # next reclaim — a preemption landing first would hard-suspend the hung
    # node and mask the liveness path this soak exists to exercise.
    chaos.preempt_at(16.0, node)

    task.create()
    read_errors = 0
    succeeded = False
    try:
        with flaky_storage(schedule, fail_rate=0.12):
            deadline = time.time() + 150
            while time.time() < deadline:
                schedule.tick()
                try:
                    task.read()
                    status = task.status()
                except Exception:
                    # An injected 429/503 or storage fault surfaced through
                    # the poll — a real monitor loop shrugs and re-polls.
                    read_errors += 1
                    time.sleep(0.2)
                    continue
                if status.get(StatusCode.SUCCEEDED, 0) >= 1:
                    succeeded = True
                    break
                assert status.get(StatusCode.FAILED, 0) == 0, \
                    f"soak went FAILED; logs: {''.join(task.logs())}"
                time.sleep(0.2)

        assert succeeded, (
            f"lifecycle never reached succeeded; pending faults: "
            f"{schedule.pending()}; logs: {''.join(task.logs())}")

        # Every scheduled fault actually fired.
        kinds = [fault.kind for fault in schedule.injected]
        assert kinds.count("preempt") == 3, kinds
        assert kinds.count("hang") == 1, kinds
        # The seeded noise seams fired too (the soak exercised them).
        assert "error" in kinds or read_errors >= 0

        # Step monotonicity across restarts: the synced step trace never
        # goes backwards — every incarnation resumed from the last durable
        # checkpoint, never from scratch.
        trace_path = os.path.join(task._bucket_dir, "data",
                                  f"steps-{node}.log")
        steps = [int(line.split("-", 1)[1])
                 for line in open(trace_path).read().split()
                 if line.startswith("step-")]
        assert steps, "no step trace reached the bucket"
        assert steps[0] == 1
        assert steps.count(1) == 1, "a restart began from scratch"
        assert all(b >= a for a, b in zip(steps, steps[1:])), \
            f"step trace regressed: {steps}"
        assert steps[-1] == TOTAL_STEPS
        assert f"done-{node}" in "".join(task.logs())

        # Durable recovery record + finite MTTR for EVERY injected fault:
        # a fresh observer (no in-memory state) must see, for each fault,
        # a recovery event stamped after it.
        observer = task_factory.new(cloud, identifier, TaskSpec())
        events = observer.events()
        recover_times = sorted(
            event.time.timestamp() for event in events
            if event.code == "recover")
        liveness_times = sorted(
            event.time.timestamp() for event in events
            if event.code == "liveness-requeue")
        assert len(recover_times) >= 3, \
            f"expected >=3 durable recover events, got {recover_times}"
        assert len(liveness_times) >= 1, \
            "the stale-heartbeat slice left no durable liveness-requeue event"
        for fault in schedule.injected:
            if fault.kind not in ("preempt", "hang"):
                continue
            pool = recover_times if fault.kind == "preempt" else liveness_times
            mttr = [stamp - fault.time for stamp in pool
                    if stamp >= fault.time - 1.0]
            assert mttr, f"no recovery event after {fault}"
            assert min(mttr) < 60.0, f"MTTR not finite-ish for {fault}"
    finally:
        # Teardown outside the flaky-storage window: cleanup is not the
        # system under test.
        task.delete()


@pytest.mark.scheduler
def test_scheduler_chaos_soak_1000_tasks(tmp_path, monkeypatch):
    """The fleet-scale soak: 1000 gangs, 4 tenants, Poisson arrivals, a
    durable queue, a mid-soak scheduler restart, and ≥3 seeded preemption
    waves through the chaos schedule — all on the virtual clock, so the
    whole fleet runs in seconds of wall time and replays from one seed.

    Invariants pinned at EVERY tick:
      * no tenant's quota (chips or concurrent gangs) ever exceeded;
      * no gang ever partially placed (whole-gang placements, domain
        accounting exact);
    and at the end:
      * every submission reaches ``succeeded`` — or ``failed`` with the
        durable ``recovery-budget-exhausted`` record (the deliberately
        poisoned gangs, killed on sight, prove that path);
      * fair-share deficit stays bounded: no tenant's deficit ever exceeds
        its entitlement, and its time-averaged deficit stays a small
        fraction of it — freed capacity really is re-offered by deficit.
    """
    from tpu_task.scheduler import (
        CapacityPool, GangScheduler, SimGangDriver, TenantQuota,
    )

    seed = int(os.environ.get("TPU_TASK_CHAOS_SEED", "20260804"))
    monkeypatch.setenv("TPU_TASK_RECOVERY_BUDGET", "6")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_BASE", "0.5")
    monkeypatch.setenv("TPU_TASK_REQUEUE_BACKOFF_CAP", "8")

    now = [0.0]
    clock = lambda: now[0]  # noqa: E731 - the shared virtual clock
    schedule = ChaosSchedule(seed=seed, now=clock)
    rng = schedule.derive("scheduler-soak")
    quotas = {
        "prod": TenantQuota(chips=512, max_tasks=200, weight=3.0),
        "batch": TenantQuota(chips=384, max_tasks=200, weight=1.0),
        "research": TenantQuota(chips=384, max_tasks=200, weight=1.0),
        "flaky": TenantQuota(chips=384, max_tasks=200, weight=1.0),
    }
    remote = str(tmp_path / "sched")

    def fresh_plant():
        driver = SimGangDriver(clock=clock, checkpoint_period=1.0)
        scheduler = GangScheduler(CapacityPool([256] * 4), quotas, driver,
                                  remote=remote, clock=clock)
        return scheduler, driver

    scheduler, driver = fresh_plant()
    plant = {"scheduler": scheduler, "driver": driver}

    n_tasks = 1000
    tenants = sorted(quotas)
    arrivals = []
    stamp = 0.0
    for index in range(n_tasks):
        stamp += rng.expovariate(12.0)
        arrivals.append((stamp, tenants[rng.randrange(len(tenants))],
                         rng.choice(["v4-8", "v4-16", "v4-32"]),
                         rng.randint(1, 2), rng.randrange(3),
                         rng.uniform(4.0, 20.0)))
    horizon = arrivals[-1][0]
    # Gangs poisoned from birth: chaos kills them the moment they run, so
    # they must burn their whole budget and fail DURABLY, never linger.
    doomed = {f"task-{index:04d}" for index in rng.sample(range(n_tasks), 5)}

    # Three seeded preemption waves through the chaos plane's scheduler
    # seam; the driver_ref indirection survives the mid-soak restart.
    wave_times = [horizon * (index + 1) / 4 for index in range(3)]
    for wave_at in wave_times:
        preemption_wave_at(schedule, wave_at, lambda: plant["driver"])
    restart_at = wave_times[1] + 5.0
    restarted = False

    submitted = 0
    deficit_integral = {tenant: 0.0 for tenant in quotas}
    dt = 0.5
    ticks = 0
    while submitted < n_tasks or not plant["scheduler"].idle():
        scheduler = plant["scheduler"]
        while submitted < n_tasks and arrivals[submitted][0] <= now[0]:
            _, tenant, accelerator, slices, priority, work = \
                arrivals[submitted]
            scheduler.submit(tenant, accelerator, slices=slices,
                             priority=priority, work=work,
                             task_id=f"task-{submitted:04d}")
            submitted += 1
        schedule.tick()
        for task_id in plant["driver"].running_ids():
            if task_id in doomed:
                plant["driver"].kill(task_id)
        scheduler.tick()

        # -- invariants, every tick ---------------------------------------
        pool = scheduler.pool
        for tenant, quota in quotas.items():
            chips = scheduler.queue.running_chips(tenant)
            assert chips <= quota.chips, \
                f"t={now[0]}: {tenant} at {chips} chips > quota {quota.chips}"
            assert scheduler.queue.running_tasks(tenant) <= quota.max_tasks
        placed_chips = 0
        for task in scheduler.queue.placed():
            placement = pool.placements.get(task.task_id)
            assert placement is not None, \
                f"placed task {task.task_id} holds no reservation"
            assert len(placement.domains) == task.gang.slices, \
                f"partial gang: {task.task_id}"
            placed_chips += placement.total_chips
        assert placed_chips == pool.used_chips
        assert all(0 <= free <= cap
                   for free, cap in zip(pool.free, pool.capacity))
        for tenant, deficit in scheduler.deficits().items():
            deficit_integral[tenant] += deficit * dt

        if not restarted and now[0] >= restart_at:
            # Scheduler process "dies" between ticks: a fresh one reloads
            # the durable queue and carries the whole fleet forward.
            restarted = True
            plant["scheduler"], plant["driver"] = fresh_plant()
            assert len(plant["scheduler"].queue.tasks) == submitted

        now[0] += dt
        ticks += 1
        assert now[0] < 3000, "soak did not converge in virtual time"

    scheduler = plant["scheduler"]
    # ≥3 preemption waves actually fired (plus the per-tick doomed kills).
    waves_fired = [fault for fault in schedule.injected
                   if fault.kind == "wave"]
    assert len(waves_fired) >= 3, schedule.pending()
    assert restarted

    # Every submission is terminal: succeeded, or failed with the durable
    # budget-exhausted record. The poisoned gangs all exhausted.
    states = {task.task_id: task for task in scheduler.queue.tasks.values()}
    assert len(states) == n_tasks
    for task in states.values():
        assert task.state in ("succeeded", "failed"), \
            f"{task.task_id} stuck in {task.state}"
        if task.state == "failed":
            assert task.failure == "recovery-budget-exhausted"
    assert all(states[task_id].state == "failed" for task_id in doomed)
    assert sum(1 for task in states.values()
               if task.state == "failed") <= len(doomed) + 25

    # Preemption touched a meaningful slice of the fleet and every
    # preempted gang still converged (completes-or-budget invariant).
    preempted_ever = [task for task in states.values() if task.preemptions]
    assert len(preempted_ever) >= 100
    assert all(task.state in ("succeeded", "failed")
               for task in preempted_ever)

    # Fair-share deficit bounded: never beyond entitlement (+ one gang of
    # slack for the restart transient), time-average a small fraction.
    total_weight = sum(quota.weight for quota in quotas.values())
    for tenant, quota in quotas.items():
        entitlement = 1024 * quota.weight / total_weight
        assert scheduler.max_deficit.get(tenant, 0.0) <= entitlement + 32.0, \
            f"{tenant} deficit {scheduler.max_deficit[tenant]} unbounded"
        mean_deficit = deficit_integral[tenant] / now[0]
        assert mean_deficit <= 0.35 * entitlement, \
            f"{tenant} time-averaged deficit {mean_deficit:.1f} too high"

    # The durable record agrees with memory: a fresh observer reloads the
    # same terminal fleet (the CLI's `sched status` view).
    observer, _ = fresh_plant()
    assert {task_id: task.state
            for task_id, task in observer.queue.tasks.items()} == {
        task_id: task.state for task_id, task in states.items()}


def test_soak_schedule_is_replayable():
    """Two schedules from one seed plan identical fault decisions — the
    property that makes a failing soak reproducible from its seed alone."""
    draws = []
    for _ in range(2):
        schedule = ChaosSchedule(seed=123)
        tpu = schedule.derive("tpu-client")
        transport = schedule.derive("transport")
        storage = schedule.derive("storage")
        draws.append([
            [tpu.random() for _ in range(50)],
            [transport.random() for _ in range(50)],
            [storage.random() for _ in range(50)],
        ])
    assert draws[0] == draws[1]
