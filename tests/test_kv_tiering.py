"""Tiered KV hierarchy tests (ROADMAP item 3): the HBM → host RAM →
fleet bucket pager, plus the int4 density rung that doubles what the
HBM tier holds.

The correctness spine is the same one the fleet plane pinned: a block
payload is only ever adopted under the content hash naming its exact
token prefix, so demotion/promotion/spill can replace *where* KV lives
but can never change a stream — every stream assertion here is
bit-identity against an engine with no tier (and a pool big enough to
never evict), and every quantization assertion is the recorded error
contract (|dequant - value| <= scale/2 for int4's 4-bit codes).

Two tests are tier-1 smoke pins (the int4 error property and the
demote→promote byte-identity sweep); the engine-level soaks — 5× the
HBM pool's sessions, the long-context int4 leg, preemption while
demoted, and the spill-to-bucket arm — ride the slow set.
"""

import numpy as np
import pytest

from tpu_task.storage.backends import LocalBackend

pytestmark = pytest.mark.tiering

RNG = np.random.default_rng(41)


def _micro():
    import jax
    import jax.numpy as jnp

    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        dtype=jnp.float32, vocab_size=64, d_model=32, n_layers=2,
        n_heads=4, d_head=8, d_ff=64, n_kv_heads=2)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(cfg, params, *, rng_seed=0, kv_client=None, **knobs):
    import jax

    from tpu_task.ml.serving import ServingConfig, ServingEngine

    scfg = ServingConfig(**{"slots": 2, "block_size": 4, "n_blocks": 32,
                            "max_len": 48, **knobs})
    return ServingEngine(params, cfg, scfg,
                         rng=jax.random.PRNGKey(rng_seed),
                         kv_fleet=kv_client)


def _dtypes():
    from tpu_task.ml.serving.cache import fp8_supported

    out = [None, "int8", "int4"]
    if fp8_supported():
        out.append("fp8")
    return out


# -- smoke pin 1: the int4 error contract ------------------------------------


def test_int4_roundtrip_error_property():
    """Pack/unpack is the identity on all 16 nibble codes, and the
    quantize→dequantize round trip honors |err| <= scale/2 per element
    — the contract docs/parity.md's dtype table records for the 4-bit
    rung (scale = amax/7, so worst-case error is amax/14)."""
    import jax.numpy as jnp

    from tpu_task.ml.serving.cache import (
        INT4_MAX,
        INT8_SCALE_EPS,
        dequantize_blocks,
        pack_int4,
        unpack_int4,
    )

    # All 16 signed codes survive the byte packing bit-exactly.
    codes = jnp.asarray(
        np.tile(np.arange(-8, 8, dtype=np.int8), 4).reshape(4, 16))
    assert np.array_equal(np.asarray(unpack_int4(pack_int4(codes))),
                          np.asarray(codes))

    # Random blocks: per-row scale, error bounded by scale/2.
    from tpu_task.ml.serving.cache import quantize_blocks

    vals = RNG.standard_normal((6, 4, 2, 16)).astype(np.float32)
    vals[0] *= 100.0                 # large-amplitude block
    vals[1] *= 1e-6                  # tiny block (the eps floor arm)
    packed, scale = quantize_blocks(jnp.asarray(vals), jnp.uint8)
    assert packed.dtype == jnp.uint8
    assert packed.shape == (6, 4, 2, 8)       # two codes per byte
    back = np.asarray(dequantize_blocks(packed, scale, jnp.float32))
    amax = np.abs(vals).max(axis=(1, 3))
    expect_scale = np.maximum(amax / INT4_MAX, INT8_SCALE_EPS)
    err = np.abs(back - vals)
    # Nothing clips (the amax element maps to exactly ±7), so every
    # element sits within half a quantization step of its value.
    assert (err <= expect_scale[:, None, :, None] / 2 + 1e-6).all()

    # The density claim at the bytes level: the same byte budget holds
    # ~2× the int4 blocks of int8 (codes halve; scale sidecars are
    # shared overhead).
    from tpu_task.ml.serving import ServingConfig
    from tpu_task.ml.serving.cache import kv_block_bytes

    cfg, _ = _micro()
    kw = dict(slots=2, block_size=4, n_blocks=8, max_len=16)
    b8 = kv_block_bytes(cfg, ServingConfig(kv_dtype="int8", **kw))
    b4 = kv_block_bytes(cfg, ServingConfig(kv_dtype="int4", **kw))
    budget = 1 << 20
    assert budget // b4 >= int(1.8 * (budget // b8))


# -- smoke pin 2: demote → promote byte identity -----------------------------


def test_demote_promote_byte_identity_all_dtypes():
    """The tier seam is byte-faithful for every pool dtype: stage a
    block's device slices (the demote path's non-blocking half), force
    them to bytes, park them in a HostKvTier, promote into a FRESH
    pool, and export again — identical payloads end to end. Also pins
    the tier's LRU/spill mechanics: budget eviction spills oldest-first
    into the sink, a failing sink drops (never raises), get() refreshes
    recency, and chain_depth stops at a hole."""
    import jax.numpy as jnp

    from tpu_task.ml.serving import ServingConfig, init_pools
    from tpu_task.ml.serving.cache import (
        export_block_bytes,
        split_block_bytes,
        stage_block_arrays,
        staged_block_to_bytes,
        write_block,
    )
    from tpu_task.ml.serving.offload import HostKvTier

    cfg, _ = _micro()
    for kv_dtype in _dtypes():
        scfg = ServingConfig(slots=2, block_size=4, n_blocks=8,
                             max_len=16, kv_dtype=kv_dtype)
        pools = init_pools(cfg, scfg)
        rng = np.random.default_rng(3)
        filled = []
        for layer in pools:
            out = {}
            for name, arr in layer.items():
                vals = rng.standard_normal(arr.shape[1:]).astype(
                    np.float32)
                out[name] = arr.at[3].set(
                    jnp.asarray(vals).astype(arr.dtype))
            filled.append(out)
        payload = staged_block_to_bytes(stage_block_arrays(filled, 3))
        assert payload == export_block_bytes(filled, 3)

        tier = HostKvTier(4)
        tier.put(b"h3", payload)
        promoted = tier.get(b"h3")
        assert promoted == payload
        values = split_block_bytes(promoted, cfg, scfg)
        assert values is not None
        fresh = write_block(
            init_pools(cfg, scfg), jnp.int32(5),
            [{name: jnp.asarray(leaf) for name, leaf in layer.items()}
             for layer in values])
        assert export_block_bytes(fresh, 5) == payload, kv_dtype

    # Tier mechanics (dtype-independent): LRU spill order and the sink.
    spilled = []
    tier = HostKvTier(2, spill=lambda batch: spilled.extend(batch))
    tier.put(b"a", b"pa")
    tier.put(b"b", b"pb")
    assert tier.get(b"a") == b"pa"          # refresh: b is now LRU
    tier.put(b"c", b"pc")
    assert spilled == [(b"b", b"pb")] and tier.spilled_blocks == 1
    assert b"b" not in tier and tier.get(b"a") == b"pa"
    assert tier.chain_depth([b"a", b"zz", b"c"]) == 1

    def bad_sink(batch):
        raise OSError("bucket down")

    tier = HostKvTier(1, spill=bad_sink)
    tier.put(b"a", b"pa")
    tier.put(b"b", b"pb")                   # sink fails → dropped, no raise
    assert tier.dropped_blocks == 1 and tier.spilled_blocks == 0


# -- engine-level soaks (slow set) -------------------------------------------


def _run_sessions(eng, n_sessions, turns, max_new=4):
    """Interleaved multi-turn sessions: every session submits its full
    context each turn (idle between turns — exactly the blocks the host
    tier exists to park). Returns each session's per-turn streams."""
    ctxs = [list(range(1 + s, 9 + s)) for s in range(n_sessions)]
    streams = [[] for _ in range(n_sessions)]
    for t in range(turns):
        rids = {}
        for s in range(n_sessions):
            rids[s] = eng.submit(np.asarray(ctxs[s], np.int32),
                                 max_new_tokens=max_new)
        out = eng.drain()
        for s in range(n_sessions):
            toks = out[rids[s]]
            streams[s].append(list(toks))
            ctxs[s] += list(toks) + [(3 * s + 7 * t) % 60 + 1]
    return streams


@pytest.mark.slow
@pytest.mark.parametrize("overlap", [False, True])
def test_session_soak_5x_hbm_capacity_bit_identical(overlap):
    """The capacity law: a pool that fits ~2 sessions serves 10 (5×)
    multi-turn sessions with every stream bit-identical to a no-tier
    engine whose pool never evicts — resumes ride host→HBM promotion
    (asserted via the counters), not luck."""
    cfg, params = _micro()
    n_sessions, turns = 10, 3
    knobs = dict(block_size=4, n_blocks=18, max_len=64,
                 host_offload_blocks=256, overlap=overlap)
    eng = _engine(cfg, params, **knobs)
    ref = _engine(cfg, params, n_blocks=256, max_len=64,
                  host_offload_blocks=0)
    got = _run_sessions(eng, n_sessions, turns)
    want = _run_sessions(ref, n_sessions, turns)
    assert got == want
    st = eng.stats()["tiering"]
    assert st["demoted_blocks"] > 0
    assert st["promoted_blocks"] > 0, st
    # ≥5×: sessions served vs what the HBM pool alone could hold live.
    blocks_per_session = eng.scfg.blocks_for(
        8 + turns * 5)                       # final context length
    fits = (eng.scfg.n_blocks - 1) // blocks_per_session
    assert n_sessions >= 5 * max(1, fits)


@pytest.mark.slow
def test_long_context_int4_leg_exceeds_model_dtype_pool():
    """The long-context leg: an int4 pool decodes a prompt whose KV AT
    MODEL DTYPE would not fit the pool's byte budget — the density rung
    changing what 'fits in HBM' means — with the stream bit-identical
    to an int4 engine whose pool is big enough to never feel pressure
    (same quantization, so identity is exact, not approximate)."""
    import dataclasses

    from tpu_task.ml.serving.cache import kv_token_bytes, paged_cache_bytes

    cfg, params = _micro()
    plen = 40
    knobs = dict(slots=1, block_size=4, n_blocks=14, max_len=64,
                 kv_dtype="int4", host_offload_blocks=64)
    eng = _engine(cfg, params, **knobs)
    dense_scfg = dataclasses.replace(eng.scfg, kv_dtype=None,
                                     host_offload_blocks=0)
    assert plen * kv_token_bytes(cfg, dense_scfg) > paged_cache_bytes(
        cfg, eng.scfg, eng.scfg.n_blocks)
    ref = _engine(cfg, params, slots=1, n_blocks=64, max_len=64,
                  kv_dtype="int4")
    prompt = (np.arange(plen, dtype=np.int32) * 5) % 60 + 1
    rid = eng.submit(prompt, max_new_tokens=8)
    rid_ref = ref.submit(prompt, max_new_tokens=8)
    assert eng.drain()[rid] == ref.drain()[rid_ref]


@pytest.mark.slow
def test_preemption_while_demoted_token_identical():
    """The regression the residency invariant exists for: a pool small
    enough that running requests preempt each other WHILE the prefix
    cache's tail sits demoted on the host tier — every stream must
    still be bit-identical to the pressure-free engine (preempted
    victims resume through promotion or recompute, never a wrong
    stream)."""
    cfg, params = _micro()
    eng = _engine(cfg, params, slots=3, block_size=4, n_blocks=14,
                  max_len=48, host_offload_blocks=128)
    ref = _engine(cfg, params, slots=3, n_blocks=256, max_len=48)
    prompts = [(np.arange(14, dtype=np.int32) * (s + 2)) % 60 + 1
               for s in range(6)]
    got, want = {}, {}
    for eng_, out in ((eng, got), (ref, want)):
        rids = [eng_.submit(p, max_new_tokens=10) for p in prompts]
        res = eng_.drain()
        for i, rid in enumerate(rids):
            out[i] = res[rid]
    assert got == want
    assert eng.preemption_count > 0 or eng.stats()["tiering"][
        "demoted_blocks"] > 0
    assert eng.stats()["tiering"]["demoted_blocks"] > 0


@pytest.mark.slow
def test_host_budget_spill_lands_in_bucket(tmp_path):
    """Beyond the host budget the tier spills into the kvfleet bucket
    through the content-addressed plane — and a SIBLING replica imports
    a spilled chain exactly like a published one (the spill is
    indistinguishable to importers by design)."""
    from tpu_task.serve.kvfleet import FleetKvClient

    backend = LocalBackend(str(tmp_path))
    cfg, params = _micro()
    client_a = FleetKvClient(backend, "ra", refresh_interval=0.0)
    eng = _engine(cfg, params, block_size=4, n_blocks=18, max_len=64,
                  host_offload_blocks=3, kv_client=client_a)
    _run_sessions(eng, 8, 2)
    st = eng.stats()["tiering"]
    assert st["host_spilled_blocks"] > 0, st
    assert client_a.published_blocks > 0

    # The spilled chain serves a cold sibling's admission.
    client_b = FleetKvClient(backend, "rb", refresh_interval=0.0)
    sib = _engine(cfg, params, n_blocks=64, max_len=64,
                  kv_client=client_b)
    ref = _engine(cfg, params, n_blocks=64, max_len=64)
    prompt = np.asarray(list(range(1, 9)), np.int32)
    rid = sib.submit(prompt, max_new_tokens=4)
    rid_ref = ref.submit(prompt, max_new_tokens=4)
    assert sib.drain()[rid] == ref.drain()[rid_ref]
    assert sib.fleet_hit_blocks > 0


@pytest.mark.slow
def test_prefetch_chain_promotes_host_to_hbm():
    """`prefetch_chain` generalized down the hierarchy: a router hint
    warms HBM from host RAM with no fleet plane attached at all — the
    next admission is a pure local prefix hit."""
    from tpu_task.ml.serving.cache import chain_block_hashes

    cfg, params = _micro()
    eng = _engine(cfg, params, block_size=4, n_blocks=18, max_len=64,
                  host_offload_blocks=64)
    prompt = np.asarray(list(range(2, 14)), np.int32)
    rid = eng.submit(prompt, max_new_tokens=4)
    first = eng.drain()[rid]
    # Churn until the prompt's blocks are demoted AND evicted from HBM.
    _run_sessions(eng, 6, 2)
    hashes = chain_block_hashes(prompt, eng.scfg.block_size)
    missing = [h for h in hashes if not eng._pcache.has(h)]
    assert missing, "churn failed to evict the prompt's chain"
    n = eng.prefetch_chain(hashes)
    assert n > 0
    assert all(eng._pcache.has(h) for h in hashes[:len(hashes)])
    before = eng.prefix_hit_requests
    rid2 = eng.submit(prompt, max_new_tokens=4)
    assert eng.drain()[rid2] == first
    assert eng.prefix_hit_requests == before + 1
