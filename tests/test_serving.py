"""Serving engine + paged KV cache tests (CPU, tiny shapes).

The two ``perf``-marked tests are the tier-1 smoke contract of the
continuous-batching engine: token-level equivalence with the offline
``generate`` path under greedy decoding, and no head-of-line blocking (a
short request admitted behind a long one completes without waiting for
it). The rest pin the paged/dense bit-exactness contract, the allocator,
preemption-recompute, and the checkify debug guard."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpu_task.ml.models import decoding, transformer
from tpu_task.ml.ops.attention import gqa_cached_attention
from tpu_task.ml.serving import (
    BlockAllocator,
    ServingConfig,
    ServingEngine,
)
from tpu_task.ml.serving.cache import flat_pool, gather_kv

# GQA on purpose: the paged pool must stay at KV-head width end to end.
TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_head=8, d_ff=64,
    dtype=jnp.float32, n_kv_heads=2)


@pytest.fixture(scope="module")
def params():
    return transformer.init(jax.random.PRNGKey(0), TINY)


def _generate_ref(params, prompt, max_new):
    return np.asarray(decoding.generate(
        params, TINY, jnp.asarray(prompt)[None].astype(jnp.int32),
        max_new)[0])


# -- config + allocator ------------------------------------------------------

def test_serving_config_validation():
    with pytest.raises(ValueError, match="slots"):
        ServingConfig(slots=0)
    with pytest.raises(ValueError, match="n_blocks"):
        ServingConfig(n_blocks=1)
    with pytest.raises(ValueError, match="ascending"):
        ServingConfig(prefill_buckets=(32, 16))
    with pytest.raises(ValueError, match="max_len"):
        ServingConfig(prefill_buckets=(16, 512), max_len=256,
                      prefill="bucketed", prefix_cache=False)
    scfg = ServingConfig(block_size=16, max_len=100,
                         prefill_buckets=(16, 32, 64))
    assert scfg.max_blocks_per_slot == 7     # ceil(100 / 16)
    assert scfg.bucket_for(17) == 32
    assert scfg.blocks_for(1) == 1 and scfg.blocks_for(16) == 1
    assert scfg.blocks_for(17) == 2
    with pytest.raises(ValueError, match="bucket"):
        scfg.bucket_for(10_000)


def test_block_allocator_accounting():
    alloc = BlockAllocator(8)            # block 0 scratch → 7 allocatable
    assert alloc.available == 7 and alloc.in_use == 0
    a = alloc.alloc(3)
    assert len(a) == 3 and 0 not in a and alloc.high_water == 3
    b = alloc.alloc(4)
    assert alloc.available == 0 and alloc.high_water == 7
    assert alloc.alloc(1) is None        # exhausted: None, nothing taken
    alloc.free(a)
    assert alloc.available == 3 and alloc.high_water == 7  # HWM sticks
    with pytest.raises(ValueError, match="double free"):
        alloc.free([b[0], b[0]])
    with pytest.raises(ValueError, match="invalid"):
        alloc.free([0])                  # scratch is never freeable


# -- paged/dense parity ------------------------------------------------------

def test_paged_gather_attention_bit_exact_vs_dense():
    """THE parity contract (docs/parity.md): gathering a scattered block
    pool back through the block tables and running the shared core equals
    the dense cache bit for bit at fp32 — including a pool whose unrelated
    blocks hold garbage, because masked slots contribute exactly 0.0."""
    rng = np.random.default_rng(3)
    kv, d, bs, L, slots = 2, 8, 4, 16, 3
    k_dense = jnp.asarray(rng.standard_normal((slots, L, kv, d)), jnp.float32)
    v_dense = jnp.asarray(rng.standard_normal((slots, L, kv, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((slots, 1, 4, d)), jnp.float32)
    positions = jnp.asarray([5, 9, 2])
    # Scatter the dense rows into a garbage-initialized pool through a
    # shuffled block map, then gather back.
    tables = np.zeros((slots, L // bs), np.int32)
    pool_k = np.asarray(rng.standard_normal((13, bs, kv, d)), np.float32)
    pool_v = np.asarray(rng.standard_normal((13, bs, kv, d)), np.float32)
    free = list(range(1, 13))
    rng.shuffle(free)
    for s in range(slots):
        for b in range(L // bs):
            blk = free.pop()
            tables[s, b] = blk
            pool_k[blk] = k_dense[s, b * bs:(b + 1) * bs]
            pool_v[blk] = v_dense[s, b * bs:(b + 1) * bs]
    k_view = gather_kv(flat_pool(jnp.asarray(pool_k)), jnp.asarray(tables), bs)
    v_view = gather_kv(flat_pool(jnp.asarray(pool_v)), jnp.asarray(tables), bs)
    dense = gqa_cached_attention(q, k_dense, v_dense, positions[:, None])
    paged = gqa_cached_attention(q, k_view, v_view, positions[:, None])
    assert (np.asarray(dense) == np.asarray(paged)).all()


@pytest.mark.perf
def test_engine_greedy_matches_generate(params):
    """Tier-1 serving smoke: greedy tokens from the continuous-batching
    engine are identical to ``generate``'s for the same prompts — across
    mixed lengths, slot reuse, and lazy block growth."""
    scfg = ServingConfig(slots=3, block_size=4, n_blocks=32, max_len=32,
                         prefill_buckets=(8, 16))
    eng = ServingEngine(params, TINY, scfg)
    rng = np.random.default_rng(0)
    reqs = []
    for plen, new in [(5, 6), (8, 3), (12, 9), (3, 12), (7, 1), (16, 8)]:
        prompt = rng.integers(0, TINY.vocab_size, size=plen)
        reqs.append((eng.submit(prompt, new), prompt, new))
    out = eng.drain()
    for rid, prompt, new in reqs:
        np.testing.assert_array_equal(
            np.array(out[rid]), _generate_ref(params, prompt, new))
    assert eng.allocator.referenced == 0      # every reference returned
    assert eng.allocator.high_water > 0


@pytest.mark.perf
def test_short_request_completes_before_long(params):
    """No head-of-line blocking: a short request admitted behind a
    long-running one retires as soon as ITS length hits, while the long
    one is still decoding."""
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=32, max_len=64,
                         prefill_buckets=(8,))
    eng = ServingEngine(params, TINY, scfg)
    rng = np.random.default_rng(1)
    long_rid = eng.submit(rng.integers(0, 64, size=6), 40)
    eng.step()                                 # long one admitted + decoding
    short_rid = eng.submit(rng.integers(0, 64, size=6), 3)
    while eng.poll(short_rid)["status"] != "done":
        eng.step()
    assert eng.poll(long_rid)["status"] == "running"
    assert len(eng.poll(long_rid)["tokens"]) < 40
    out = eng.drain()
    assert len(out[long_rid]) == 40 and len(out[short_rid]) == 3


# -- tensor-parallel serving (8-device virtual mesh, kv heads over tp) -------

# kv_heads == 8 so tp=8 gives every shard one kv head (its whole query
# group rides along: n_heads % kv_heads == 0 keeps groups contiguous).
TP8 = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=8, d_head=8, d_ff=64,
    dtype=jnp.float32, n_kv_heads=8)


def _tp_mesh(n=8):
    from tpu_task.ml.parallel.mesh import make_mesh

    return make_mesh(n, axis_names=("tp",), axis_sizes=(n,))


@pytest.mark.perf
def test_engine_tp8_greedy_matches_single_chip():
    """Tier-1 sharded-serving smoke: the tp=8 engine's greedy token streams
    are IDENTICAL to the single-chip engine's on the same requests — mixed
    lengths, slot reuse, lazy block growth, pools donated and kv-head
    sharded. (Logits agree to accumulation-order tolerance; token identity
    is the pinned contract — docs/parity.md.)"""
    params = transformer.init(jax.random.PRNGKey(0), TP8)
    scfg = ServingConfig(slots=3, block_size=4, n_blocks=32, max_len=32,
                         prefill_buckets=(8, 16))
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, TP8.vocab_size, size=plen), new)
            for plen, new in [(5, 6), (8, 3), (12, 9), (3, 12), (16, 8)]]

    def run(mesh):
        eng = ServingEngine(params, TP8, scfg, mesh=mesh)
        rids = [eng.submit(p, n) for p, n in reqs]
        out = eng.drain()
        assert eng.allocator.referenced == 0
        return [out[r] for r in rids], eng

    single, _ = run(None)
    sharded, eng = run(_tp_mesh())
    assert single == sharded
    # The pools really shard: kv-head axis over tp, 1/8 of the bytes per
    # device, and the donated round-trip kept the layout.
    from jax.sharding import PartitionSpec

    k0 = eng.pools[0]["k"]
    assert k0.sharding.spec == PartitionSpec(None, None, "tp", None)
    assert k0.addressable_shards[0].data.nbytes * 8 == k0.nbytes
    assert eng.stats()["kv_pool_bytes_per_shard"] * 8 == \
        eng.stats()["kv_pool_bytes"]


def test_engine_mesh_validation_rejects_indivisible_kv_heads(params):
    """TINY has kv_heads=2: an 8-way tp mesh cannot shard the pool's
    kv-head axis — loud error at construction, not a wrong answer later."""
    with pytest.raises(ValueError, match="kv_heads"):
        ServingEngine(params, TINY, ServingConfig(), mesh=_tp_mesh())


def test_engine_tp8_decodes_pool_exceeding_single_chip_budget():
    """THE multichip exit criterion: a KV pool bigger than one chip's
    (notional) budget decodes across tp=8, each device holding exactly 1/8
    of the pool — the serving analogue of model-parallel training."""
    from tpu_task.ml.serving.cache import kv_shard_bytes, paged_cache_bytes

    budget = 8 * 1024 * 1024          # per-"chip" KV budget for this test
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=8, d_head=16,
        d_ff=64, dtype=jnp.float32, n_kv_heads=8)
    scfg = ServingConfig(slots=2, block_size=8, n_blocks=1024, max_len=64,
                         prefill_buckets=(8,))
    pool_bytes = paged_cache_bytes(cfg, scfg, scfg.n_blocks)
    assert pool_bytes > budget                      # won't fit one chip
    assert kv_shard_bytes(cfg, scfg, scfg.n_blocks, 8) <= budget

    params = transformer.init(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params, cfg, scfg, mesh=_tp_mesh())
    for layer in eng.pools:
        for leaf in layer.values():
            assert leaf.addressable_shards[0].data.nbytes * 8 == leaf.nbytes
    prompt = np.random.default_rng(1).integers(0, cfg.vocab_size, size=5)
    rid = eng.submit(prompt, 8)
    out = eng.drain()[rid]
    assert len(out) == 8
    assert all(0 <= t < cfg.vocab_size for t in out)
    assert eng.allocator.referenced == 0


def test_engine_tp8_prefill_logits_match_to_tolerance():
    """The tolerance half of the sharded-serving contract: tp-sharded
    logits equal the single-chip program's to accumulation-order tolerance
    (the wo/unembed contractions partial-sum across shards), while the
    token streams above stay exactly equal."""
    params = transformer.init(jax.random.PRNGKey(0), TP8)
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=16, max_len=16,
                         prefill_buckets=(8,))
    prompt = np.random.default_rng(2).integers(0, TP8.vocab_size, size=6)

    def prefill_logits(mesh):
        eng = ServingEngine(params, TP8, scfg, mesh=mesh)
        table = np.zeros((scfg.max_blocks_per_slot,), np.int32)
        table[:2] = eng.allocator.alloc(2)
        padded = np.zeros((1, 8), np.int32)
        padded[0, :len(prompt)] = prompt
        logits, _pools = eng._prefill_fn(
            eng.params, jnp.asarray(padded), jnp.int32(len(prompt)),
            jnp.asarray(table), eng.pools)
        return np.asarray(logits)

    single, sharded = prefill_logits(None), prefill_logits(_tp_mesh())
    np.testing.assert_allclose(single, sharded, atol=1e-5, rtol=1e-5)


# -- scheduler behaviors -----------------------------------------------------

def test_engine_sampling_deterministic_per_request_under_any_schedule(params):
    """Sampling keys derive from the request key alone (fold_in per token
    index), so a request's stream is identical whether it runs solo or
    co-scheduled — and across preemption-recompute."""
    prompts = [np.random.default_rng(7).integers(0, 64, size=6)
               for _ in range(4)]

    def run(slots):
        scfg = ServingConfig(slots=slots, block_size=4, n_blocks=32,
                             max_len=32, prefill_buckets=(8,))
        eng = ServingEngine(params, TINY, scfg, rng=jax.random.PRNGKey(42))
        rids = [eng.submit(p, 8, temperature=0.9, top_p=0.8)
                for p in prompts]
        out = eng.drain()
        return [out[r] for r in rids]

    assert run(1) == run(4)


def test_engine_pool_exhaustion_preempts_and_still_matches_generate(params):
    """A pool far too small for the offered load forces recompute
    preemptions — results must still be exact, every block must come back,
    and the high-water mark must honor the pool bound."""
    scfg = ServingConfig(slots=4, block_size=4, n_blocks=9, max_len=24,
                         prefill_buckets=(8,))
    eng = ServingEngine(params, TINY, scfg)
    rng = np.random.default_rng(2)
    reqs = []
    for _ in range(4):
        prompt = rng.integers(0, 64, size=6)
        reqs.append((eng.submit(prompt, 14), prompt))
    out = eng.drain()
    assert sum(eng.request(r).preemptions for r, _ in reqs) > 0
    for rid, prompt in reqs:
        np.testing.assert_array_equal(
            np.array(out[rid]), _generate_ref(params, prompt, 14))
    assert eng.allocator.referenced == 0
    assert eng.allocator.high_water <= scfg.n_blocks - 1


def test_engine_eos_retires_early_and_prefix_matches(params):
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=32, max_len=32,
                         prefill_buckets=(8,))
    eng = ServingEngine(params, TINY, scfg)
    prompt = np.random.default_rng(4).integers(0, 64, size=5)
    plain = _generate_ref(params, prompt, 8)
    eos = int(plain[2])
    rid = eng.submit(prompt, 8, eos_token=eos)
    out = eng.drain()[rid]
    assert out == list(plain[:3])             # stops AT the eos, inclusive
    assert eng.allocator.referenced == 0


def test_engine_prefill_bucket_padding_has_no_effect(params):
    """The same prompt through a tighter and a looser bucket produces the
    same tokens — pad rows never reach an unmasked read. (Pinned to the
    legacy bucketed path: the chunked default never pads to a bucket, so
    only prefill="bucketed" exercises the pad-row masking.)"""
    prompt = np.random.default_rng(5).integers(0, 64, size=5)

    def run(buckets):
        scfg = ServingConfig(slots=2, block_size=4, n_blocks=32, max_len=32,
                             prefill_buckets=buckets, prefill="bucketed",
                             prefix_cache=False)
        eng = ServingEngine(params, TINY, scfg)
        rid = eng.submit(prompt, 7)
        return eng.drain()[rid]

    assert run((8,)) == run((16,)) == list(_generate_ref(params, prompt, 7))


def test_engine_submit_validation_and_poll(params):
    # Legacy bucketed prefill: the bucket-fit check only applies there
    # (chunked admits any prompt up to max_len).
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=8, max_len=24,
                         prefill_buckets=(8,), prefill="bucketed",
                         prefix_cache=False)
    eng = ServingEngine(params, TINY, scfg)
    prompt = np.zeros((5,), np.int32)
    with pytest.raises(ValueError, match="at least one token"):
        eng.submit(np.zeros((0,), np.int32), 2)      # empty prompt
    with pytest.raises(ValueError, match="bucket"):
        eng.submit(np.zeros((9,), np.int32), 2)      # prompt > largest bucket
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(prompt, 100)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(prompt, 0)
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(prompt, 2, top_p=0.5)             # greedy ignores top_p
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(prompt, 2, temperature=1.0, top_p=1.5)
    rid = eng.submit(prompt, 2)
    assert eng.poll(rid) == {"status": "queued", "tokens": []}
    with pytest.raises(RuntimeError, match="not done"):
        eng.result(rid)
    eng.drain()
    assert eng.poll(rid)["status"] == "done"
    assert len(eng.result(rid)) == 2
    stats = eng.stats()
    assert stats["kv_high_water_bytes"] < stats["kv_dense_worst_case_bytes"]


# -- checkify debug guard ----------------------------------------------------

def test_checkify_guard_trips_on_traced_overflow(params, monkeypatch):
    """The documented hard contract (decoding.py): a TRACED ``start``
    overflowing ``max_len`` corrupts silently — under TPU_TASK_CHECKIFY=1
    a checkify-functionalized caller gets a loud error instead."""
    monkeypatch.setenv("TPU_TASK_CHECKIFY", "1")
    from jax.experimental import checkify

    caches = decoding.init_cache(TINY, batch=1, max_len=4)
    tokens = jnp.zeros((1, 2), jnp.int32)
    fn = jax.jit(checkify.checkify(
        lambda start: decoding.forward_with_cache(
            params, TINY, tokens, caches, start)[0]))
    err, _ = fn(jnp.int32(3))                  # 3 + 2 > 4: overflow
    assert err.get() is not None and "overflow" in str(err.get())
    err, _ = fn(jnp.int32(2))                  # 2 + 2 == 4: in bounds
    assert err.get() is None


def test_checkify_guard_is_noop_by_default(params, monkeypatch):
    """Without the env flag the guard must not emit a check — plain jit
    callers (all of production) would fail to trace otherwise."""
    monkeypatch.delenv("TPU_TASK_CHECKIFY", raising=False)
    caches = decoding.init_cache(TINY, batch=1, max_len=8)
    tokens = jnp.zeros((1, 2), jnp.int32)
    logits, _ = jax.jit(
        lambda start: decoding.forward_with_cache(
            params, TINY, tokens, caches, start))(jnp.int32(0))
    assert logits.shape == (1, TINY.vocab_size)


def test_engine_debug_mode_runs_checkified(params, monkeypatch):
    """TPU_TASK_CHECKIFY=1 wraps every engine program in checkify: a clean
    run throws nothing and still matches generate. (The reference runs
    BEFORE the flag flips: under the flag, the guard inside generate's scan
    requires its caller to functionalize too — that is the point.)"""
    prompt = np.random.default_rng(6).integers(0, 64, size=5)
    ref = _generate_ref(params, prompt, 4)
    monkeypatch.setenv("TPU_TASK_CHECKIFY", "1")
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=16, max_len=16,
                         prefill_buckets=(8,))
    eng = ServingEngine(params, TINY, scfg)
    assert eng.debug
    rid = eng.submit(prompt, 4)
    np.testing.assert_array_equal(np.array(eng.drain()[rid]), ref)
