"""SLA actuation plane (PR 18), tier-1 pins: the header vocabulary, the
degrade ladder's class ordering, EDF inside the fairness invariants,
SLO-aware victim selection, slack-ordered engine admission, the router's
shed gate, and the SLA-aware autoscalers.

Everything here is pure host Python (no engine compile, no loopback
HTTP) — the end-to-end brownout behavior lives in test_sla_soak.py
(`make sla-soak`)."""

import io
import json
import urllib.error
from collections import deque
from types import SimpleNamespace

import pytest

from tpu_task.obs.sla import (
    DEFAULT_CLASS,
    DegradeLadder,
    MAX_RUNG,
    RUNG_NOSPEC,
    RUNG_SHED,
    class_rank,
    format_sla_header,
    parse_sla_header,
)
from tpu_task.scheduler.pool import CapacityPool, select_victims
from tpu_task.scheduler.queue import GangSpec, QueuedTask, fair_share_order
from tpu_task.serve.autoscale import QueueDepthAutoscaler, SlaAutoscaler
from tpu_task.serve.router import Router, _Replica

pytestmark = pytest.mark.sla


# -- header vocabulary ---------------------------------------------------------


def test_sla_header_roundtrip():
    assert parse_sla_header(format_sla_header("premium", 1234.56)) == \
        ("premium", 1234.6)
    assert parse_sla_header(format_sla_header("best_effort")) == \
        ("best_effort", None)


def test_sla_header_parse_is_permissive():
    """Garbled SLA metadata degrades to (standard, no deadline) — never
    to a rejected request."""
    assert parse_sla_header(None) == (DEFAULT_CLASS, None)
    assert parse_sla_header("") == (DEFAULT_CLASS, None)
    assert parse_sla_header(";") == (DEFAULT_CLASS, None)
    assert parse_sla_header("premium;not-a-number") == ("premium", None)
    assert parse_sla_header("premium;-5") == ("premium", 0.0)
    assert class_rank("no-such-class") == class_rank(DEFAULT_CLASS)


# -- the degrade ladder --------------------------------------------------------


def test_ladder_escalates_and_deescalates_asymmetrically():
    ladder = DegradeLadder(escalate_after=1, clear_after=2)
    assert ladder.observe(True) == 1
    assert ladder.observe(True) == 2
    # One clear beat is not enough to convince the ladder down.
    assert ladder.observe(False) == 2
    assert ladder.observe(False) == 1
    assert ladder.observe(False) == 2 - 1  # needs two MORE clear beats
    assert ladder.observe(False) == 0
    assert ladder.observe(False) == 0      # floor


def test_ladder_brownout_order_least_protected_first():
    """The brownout contract: best_effort walks every rung before
    premium starts, and the ladder can NEVER shed premium."""
    ladder = DegradeLadder(clamp_max_new=4, escalate_after=1)
    for _ in range(MAX_RUNG + 3):
        ladder.observe(True)
    assert ladder.rung == MAX_RUNG
    best = ladder.plan("best_effort", 32)
    std = ladder.plan("standard", 32)
    prem = ladder.plan("premium", 32)
    assert best["shed"] and std["shed"]
    assert not prem["shed"]                       # ladder ceiling
    assert prem["no_spec"] and prem["max_new"] == 4
    # Mid-ladder: the front has reached best_effort only.
    ladder = DegradeLadder(clamp_max_new=4, escalate_after=1)
    ladder.observe(True)                          # rung 1
    assert ladder.plan("best_effort", 32)["max_new"] == 4
    assert ladder.plan("premium", 32)["max_new"] == 32
    ladder.observe(True)                          # rung 2
    assert ladder.plan("best_effort", 32)["no_spec"]
    assert not ladder.plan("standard", 32)["no_spec"]
    ladder.observe(True)                          # rung 3
    assert ladder.plan("best_effort", 32)["shed"]
    assert not ladder.plan("standard", 32)["shed"]
    assert RUNG_SHED - class_rank("premium") < RUNG_NOSPEC


# -- EDF inside the scheduler's fairness invariants ----------------------------


def _task(task_id, *, tenant="a", priority=0, seq=0, deadline=-1.0):
    return QueuedTask(task_id=task_id, tenant=tenant,
                      gang=GangSpec("v4-8"), priority=priority,
                      submit_seq=seq, deadline=deadline)


def test_fair_share_edf_within_tenant_and_priority():
    tasks = [
        _task("late", seq=0, deadline=90.0),
        _task("none", seq=1),
        _task("soon", seq=2, deadline=10.0),
    ]
    order = fair_share_order(tasks, {}, {"a": 1.0})
    assert [t.task_id for t in order] == ["soon", "late", "none"]


def test_edf_cannot_cross_priority_or_tenant():
    """EDF lives strictly inside (tenant, priority): a tight deadline
    neither outranks a higher-priority sibling nor jumps the fair-share
    order across tenants."""
    tasks = [
        _task("hi-no-deadline", priority=2, seq=0),
        _task("lo-tight", priority=0, seq=1, deadline=0.001),
    ]
    order = fair_share_order(tasks, {}, {"a": 1.0})
    assert [t.task_id for t in order] == ["hi-no-deadline", "lo-tight"]
    tasks = [
        _task("glut-tight", tenant="glut", seq=0, deadline=0.001),
        _task("lean-late", tenant="lean", seq=1, deadline=500.0),
    ]
    # lean is the deficient tenant: its task heads the order no matter
    # how tight glut's deadline is.
    order = fair_share_order(tasks, {"glut": 32, "lean": 0},
                             {"glut": 1.0, "lean": 1.0})
    assert [t.task_id for t in order] == ["lean-late", "glut-tight"]


def test_no_deadlines_is_exactly_the_pre_sla_order():
    tasks = [_task("t0", seq=0), _task("t1", seq=1), _task("t2", seq=2)]
    order = fair_share_order(tasks, {}, {"a": 1.0})
    assert [t.task_id for t in order] == ["t0", "t1", "t2"]


def test_select_victims_prefers_most_slack():
    """Among equally-reclaimable gangs, the one with the MOST slack
    (deadline-less counting as infinite) dies first — reclaiming from
    the task that can best afford the requeue."""
    pool = CapacityPool([8])

    def place(task_id, deadline):
        task = QueuedTask(task_id=task_id, tenant="glut",
                          gang=GangSpec("v4-8"), priority=1,
                          state="placed", placed_at=1.0,
                          deadline=deadline)
        assert pool.try_place(task) is not None
        return task

    placed = [place("tight", 5.0), place("loose", -1.0)]
    candidate = QueuedTask(task_id="new", tenant="starved",
                           gang=GangSpec("v4-8"), priority=1)
    victims = select_victims(candidate, placed, pool,
                             {"glut": 8, "starved": 0},
                             {"glut": 1.0, "starved": 1.0})
    assert [v.task_id for v in victims] == ["loose"]


def test_queued_task_deadline_roundtrips_with_pre_sla_records():
    task = _task("t", deadline=12.5)
    assert QueuedTask.from_json(task.to_json()).deadline == 12.5
    legacy = _task("t").to_json()
    legacy.pop("deadline")                  # a pre-SLA durable record
    assert QueuedTask.from_json(legacy).deadline == -1.0


# -- slack-ordered engine admission --------------------------------------------


def test_engine_admission_is_edf_with_fifo_fallback():
    from tpu_task.ml.serving.engine import ServingEngine
    eng = object.__new__(ServingEngine)
    # EDF: earliest deadline wins; deadline-less requests go last.
    eng._queue = deque(SimpleNamespace(deadline=d)
                      for d in (None, 30.0, 10.0))
    assert ServingEngine._next_admit_index(eng) == 2
    # No deadlines anywhere: index 0 — the historical FIFO (a preempted
    # request re-queued at the head keeps its place).
    eng._queue = deque(SimpleNamespace(deadline=None) for _ in range(3))
    assert ServingEngine._next_admit_index(eng) == 0
    # Class outranks deadline: a premium request with the LATER deadline
    # still admits before same-deadline-or-earlier best_effort — the
    # ladder makes degraded best_effort cheap, and cheap work winning
    # EDF ties by arrival would starve the protected class.
    eng._queue = deque([
        SimpleNamespace(deadline=10.0, slo_class="best_effort"),
        SimpleNamespace(deadline=30.0, slo_class="premium"),
        SimpleNamespace(deadline=20.0, slo_class="premium"),
    ])
    assert ServingEngine._next_admit_index(eng) == 2


# -- the router's shed gate ----------------------------------------------------


def _router_with_clock(t0=100.0):
    state = {"t": t0}
    router = Router(seed=0, clock=lambda: state["t"])
    return router, state


def test_shed_gate_expired_slack_sheds_unconditionally():
    router, state = _router_with_clock()
    fid = router.submit([1, 2, 3], 8, deadline_ms=50.0)
    request = router.request(fid)
    cold = _Replica(name="r0", url="http://x")
    assert not router._unmeetable(request, cold)
    state["t"] += 0.06                      # past the deadline
    assert router._unmeetable(request, cold)


def test_shed_gate_never_sheds_on_a_cold_replica():
    """No observations → no estimate arm: a cold fleet must not shed on
    guesses (the regression that would refuse the first request ever)."""
    router, _ = _router_with_clock()
    fid = router.submit([1], 8, deadline_ms=10.0)
    cold = _Replica(name="r0", url="http://x")
    assert not router._unmeetable(router.request(fid), cold)


def test_shed_gate_estimates_and_protects_by_class():
    """The estimate arm sheds when observed service cannot fit the
    slack — and protected classes get margin, so the gate can never
    invert the ladder's brownout order."""
    router, _ = _router_with_clock()
    hot = _Replica(name="r0", url="http://x",
                   ttft_ewma=0.05, tok_ewma=0.01)
    # est = 50ms + 7*10ms = 120ms against 100ms slack: best_effort
    # sheds (1.0x margin), premium does not (2.0x margin).
    be = router.request(router.submit(
        [1], 8, slo_class="best_effort", deadline_ms=100.0))
    prem = router.request(router.submit(
        [1], 8, slo_class="premium", deadline_ms=100.0))
    assert router._unmeetable(be, hot)
    assert not router._unmeetable(prem, hot)
    # Far past even the premium margin (est > 2x slack) sheds premium
    # too: an individually unmeetable deadline is not worth dispatching.
    prem_tight = router.request(router.submit(
        [1], 8, slo_class="premium", deadline_ms=40.0))
    assert router._unmeetable(prem_tight, hot)


def test_ladder_beats_drive_router_rung_and_stats():
    router = Router(seed=0, ladder=DegradeLadder(escalate_after=1))
    for _ in range(RUNG_SHED):
        router.note_alerts(["burn"])
    stats = router.stats()["sla"]
    assert stats["rung"] == RUNG_SHED
    fid = router.submit([1, 2], 8, slo_class="best_effort")
    request = router.request(fid)
    assert request.status == "shed"         # laddered shed, no replica
    assert request.retry_after_s == router.shed_retry_after_s
    with pytest.raises(RuntimeError):
        router.result(fid)
    assert router.stats()["sla"]["classes"]["best_effort"]["shed"] == 1


# -- the 429 protocol (router side, fake transport) ----------------------------


def _http_429(body: dict) -> urllib.error.HTTPError:
    return urllib.error.HTTPError(
        "http://fake/submit", 429, "busy", None,
        io.BytesIO(json.dumps(body).encode()))


def _router_with_fake_429(body: dict, **kwargs):
    router = Router(seed=0, **kwargs)
    router.set_replicas({"r0": {"url": "http://fake", "boot_id": "b0"}})

    def fake_call(replica, method, path, data=None, headers=None):
        if path == "/submit":
            raise _http_429(body)
        return {"slots": 4}

    router._call = fake_call
    return router


def test_429_busy_never_quarantines_a_healthy_replica():
    """The satellite-1 regression pin: a healthy-but-full replica answers
    429; the router must keep the request queued and the replica in good
    standing — quarantining on fullness would amplify overload into a
    fleet-wide outage."""
    router = _router_with_fake_429({"error": "overloaded",
                                    "overloaded": True})
    fid = router.submit([1, 2, 3], 8)
    request = router.request(fid)
    assert request.status == "queued"
    replica = router._replicas["r0"]
    assert replica.healthy
    assert replica.quarantined_until == 0.0
    assert replica.faults == 0


def test_429_draining_body_quarantines_like_the_legacy_409():
    router = _router_with_fake_429({"error": "draining", "draining": True})
    fid = router.submit([1, 2, 3], 8)
    assert router.request(fid).status == "queued"
    replica = router._replicas["r0"]
    assert not replica.healthy
    assert replica.quarantined_until == float("inf")


def test_429_with_expired_deadline_is_a_terminal_shed(monkeypatch):
    """A 429 landing after the deadline has expired proves the shed gate
    right: durable `shed` terminal with Retry-After, and the refusing
    replica still healthy."""
    router = _router_with_fake_429({"error": "overloaded",
                                    "overloaded": True})
    # Bypass the estimate gate to isolate the 429 arm; the deadline is
    # already in the past when the refusal comes back.
    monkeypatch.setattr(router, "_unmeetable", lambda *a: False)
    fid = router.submit([1, 2, 3], 8, deadline_ms=-50.0)
    request = router.request(fid)
    assert request.status == "shed"
    assert request.retry_after_s == router.shed_retry_after_s
    assert router._replicas["r0"].healthy
    with pytest.raises(RuntimeError, match="shed"):
        router.result(fid)
    # Durable: further pumps never resurrect a shed terminal.
    router.pump(wait_ms=0)
    assert router.request(fid).status == "shed"


# -- SLA-aware autoscaling -----------------------------------------------------


def test_queue_depth_autoscaler_attainment_gate_prevents_flap():
    """At-capacity-but-meeting-SLO must not scale up (and must not
    flap): backlog votes are vetoed while attainment holds, and the
    hysteresis counter resets so a later real breach still needs full
    patience."""
    policy = QueueDepthAutoscaler(patience=2, high=2.0, low=0.25)
    for _ in range(6):
        assert policy.observe(8, 2, busy=8, attainment=1.0) == 2
    assert policy.decisions == []
    # The same pressure with the SLO breached scales up after patience.
    assert policy.observe(8, 2, busy=8, attainment=0.5) == 2
    assert policy.observe(8, 2, busy=8, attainment=0.5) == 3
    assert policy.decisions == ["up:2->3"]
    # Pre-SLA callers (no attainment sample) keep the PR 13 behavior.
    policy = QueueDepthAutoscaler(patience=1)
    assert policy.observe(8, 2, busy=8) == 3


def test_sla_autoscaler_scales_on_the_objective_with_cooldown():
    state = {"t": 0.0}
    policy = SlaAutoscaler(ttft_p99_target_s=1.0, attainment_target=0.99,
                           downscale_margin=0.5, cooldown_s=10.0,
                           clock=lambda: state["t"])
    # Breaching p99 scales up; the next breach inside the cooldown is
    # ignored (capacity has not landed yet).
    assert policy.observe(4, 2, ttft_p99=2.0, attainment=1.0) == 3
    state["t"] = 5.0
    assert policy.observe(4, 3, ttft_p99=2.0, attainment=1.0) == 3
    state["t"] = 11.0
    assert policy.observe(4, 3, ttft_p99=2.0, attainment=1.0) == 4
    # SLO met exactly is a fleet sized exactly — only comfortable
    # margin (p99 <= target*margin, empty backlog) scales down.
    state["t"] = 30.0
    assert policy.observe(0, 4, ttft_p99=0.9, attainment=1.0) == 4
    assert policy.observe(0, 4, ttft_p99=0.4, attainment=1.0) == 3
    # Missing samples are neutral: never scale on absent evidence.
    state["t"] = 50.0
    assert policy.observe(0, 3, ttft_p99=None, attainment=None) == 3
    assert policy.decisions == ["up:2->3", "up:3->4", "down:4->3"]
