"""Fleet-wide KV plane tests (ROADMAP item 2): block export/import
bit-faithfulness, the delta-synced bucket index and its staleness
contract, block-aligned affinity + cached-depth routing, cross-engine
import stream identity, and — in the slow subset — the
cold-replica-joins-mid-soak and prefill/decode-split legs through the
whole serve subsystem.

The exactness spine: a block payload is only ever adopted under the
content hash that names its exact token prefix, so an import can replace
a prefill but can never change a stream — every stream assertion here is
bit-identity against an unshared single engine.
"""

import tempfile

import numpy as np
import pytest

from tpu_task.storage.backends import LocalBackend

pytestmark = pytest.mark.kvfleet

RNG = np.random.default_rng(99)


def _micro():
    import jax
    import jax.numpy as jnp

    from tpu_task.ml.models import transformer

    cfg = transformer.TransformerConfig(
        dtype=jnp.float32, vocab_size=64, d_model=32, n_layers=2,
        n_heads=4, d_head=8, d_ff=64, n_kv_heads=2)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(cfg, params, *, rng_seed=0, kv_client=None, **knobs):
    import jax

    from tpu_task.ml.serving import ServingConfig, ServingEngine

    scfg = ServingConfig(**{"slots": 2, "block_size": 4, "n_blocks": 32,
                            "max_len": 48, **knobs})
    return ServingEngine(params, cfg, scfg,
                         rng=jax.random.PRNGKey(rng_seed),
                         kv_fleet=kv_client)


# -- block payload export/import ---------------------------------------------


@pytest.mark.parametrize("kv_dtype", [None, "int8", "fp8", "int4"])
def test_block_payload_roundtrip_bit_faithful(kv_dtype):
    """export → split → write into a FRESH pool → export again is
    byte-identical, for model-dtype and quantized (codes + scale
    sidecars) pools alike — the block-shipping exactness contract's
    mechanical half."""
    import jax.numpy as jnp

    from tpu_task.ml.serving import ServingConfig, init_pools
    from tpu_task.ml.serving.cache import (
        block_payload_nbytes,
        export_block_bytes,
        fp8_supported,
        split_block_bytes,
        write_block,
    )

    if kv_dtype == "fp8" and not fp8_supported():
        pytest.skip("no float8_e4m3fn support in this jax build")
    cfg, _ = _micro()
    scfg = ServingConfig(slots=2, block_size=4, n_blocks=8, max_len=16,
                         kv_dtype=kv_dtype)
    pools = init_pools(cfg, scfg)
    # Fill block 3 with distinctive values through plain device writes.
    rng = np.random.default_rng(7)
    filled = []
    for layer in pools:
        out = {}
        for name, arr in layer.items():
            vals = rng.standard_normal(arr.shape[1:]).astype(np.float32)
            out[name] = arr.at[3].set(jnp.asarray(vals).astype(arr.dtype))
        filled.append(out)
    payload = export_block_bytes(filled, 3)
    assert len(payload) == block_payload_nbytes(cfg, scfg)
    values = split_block_bytes(payload, cfg, scfg)
    assert values is not None
    fresh = init_pools(cfg, scfg)
    imported = write_block(
        fresh, jnp.int32(5),
        [{name: jnp.asarray(leaf) for name, leaf in layer.items()}
         for layer in values])
    assert export_block_bytes(imported, 5) == payload
    # A torn/foreign payload is a miss, never an exception.
    assert split_block_bytes(payload[:-1], cfg, scfg) is None


def test_fleet_index_delta_sync_merge_and_staleness(tmp_path):
    """Two publishers' shards merge into one hash→source view; a chain
    with a hole stops at the hole; a stale index entry (block object
    gone from the bucket) degrades to a fetch miss — the
    never-a-wrong-stream arm of the staleness contract."""
    from tpu_task.serve.kvfleet import FleetKvClient, FleetKvIndex

    backend = LocalBackend(str(tmp_path))
    index_a = FleetKvIndex(backend, namespace="kvfleet/x",
                           refresh_interval=0.0)
    index_a.publish("ra", {"aa": 3, "bb": 3})
    index_b = FleetKvIndex(backend, namespace="kvfleet/x",
                           refresh_interval=0.0)
    index_b.publish("rb", {"cc": 3})
    index_b.refresh(force=True)
    assert "aa" in index_b and "bb" in index_b and "cc" in index_b
    assert index_b.source_of("aa") == "ra"
    assert index_b.chain_depth(["aa", "bb", "cc"]) == 3
    # A hole stops the chain: blocks past it would leave a KV gap.
    assert index_b.chain_depth(["aa", "zz", "cc"]) == 1
    # Repeated refreshes ride the conditional validators (no content
    # change → same merged view, exercised via the 304/NOT_MODIFIED arm).
    index_b.refresh(force=True)
    assert index_b.chain_depth(["aa", "bb", "cc"]) == 3
    # A publisher shard deleted from the bucket drops out on refresh.
    backend.delete("kvfleet/x/index/ra.json")
    index_b.refresh(force=True)
    assert "aa" not in index_b and "cc" in index_b

    # Client-level staleness: an advertised hash whose block object is
    # gone answers None (degrade to local prefill), and counts the miss.
    cfg, _ = _micro()
    from tpu_task.ml.serving import ServingConfig

    client = FleetKvClient(backend, "rc", refresh_interval=0.0)
    client.bind(cfg, ServingConfig(slots=2, block_size=4, n_blocks=8,
                                   max_len=16))
    client.index.publish("rc", {"dd" * 16: 1})
    assert client.fetch(bytes.fromhex("dd" * 16)) is None
    assert client.fetch_misses == 1


# -- router policy ------------------------------------------------------------


def _bare_router(n=2, **kwargs):
    from tpu_task.serve import Router

    router = Router(seed=0, block_size=4, **kwargs)
    router.set_replicas({
        f"r{i}": {"url": f"http://127.0.0.1:{9000 + i}", "boot_id": f"b{i}"}
        for i in range(n)})
    return router


def test_affinity_key_is_block_aligned_on_chain_hashes():
    """The PR 10 affinity bug: keying on the first ``affinity_tokens``
    raw ids split prompts that share every FULL cache block but diverge
    inside the trailing partial block. The fixed key is the chain hash
    of the longest full-block prefix of the window — affinity
    granularity IS prefix-cache granularity, pinned by equality with
    ``cache.chain_block_hashes``."""
    from tpu_task.ml.serving import chain_block_hashes

    router = _bare_router(affinity_tokens=10)     # NOT block-aligned
    shared = list(range(1, 9))                    # two full 4-token blocks
    a = shared + [50, 51]                         # diverge inside the
    b = shared + [60, 61]                         # ...partial 3rd block
    assert router._affinity_key(a) == router._affinity_key(b)
    assert router.pick(a).name == router.pick(b).name
    # Diverging inside a full block still separates.
    c = [1, 2, 99, 4] + shared[4:] + [50, 51]
    assert router._affinity_key(a) != router._affinity_key(c)
    # The router's chain spelling is EXACTLY the engine cache's, so the
    # depth/affinity keys name the same prefixes replicas actually hold.
    assert router._chain_hashes(a) == chain_block_hashes(np.asarray(a), 4)


def test_cached_depth_beats_affinity_and_raises_spill_threshold():
    from tpu_task.serve import Router

    router = _bare_router(n=3, spill_load=2, spill_depth_weight=1.0)
    prompt = list(range(1, 13))                   # three full blocks
    hashes = router._chain_hashes(prompt)
    affinity_pick = router.pick(prompt).name
    other = next(name for name in router._replicas
                 if name != affinity_pick)
    # The other replica served this prefix before: depth wins the pick.
    Router._note_served(router._replicas[other], hashes)
    assert router.pick(prompt).name == other
    # Spilling away from a depth-3 replica needs load imbalance of
    # spill_load + depth = 5, not 2.
    router._replicas[other].load = 4
    assert router.pick(prompt).name == other      # 4 - 0 < 5: stays
    router._replicas[other].load = 5
    spilled = router.pick(prompt).name
    assert spilled != other                       # 5 - 0 >= 5: spills
    # A zero-depth prompt spills at the plain threshold.
    cold = list(range(40, 52))
    cold_pick = router.pick(cold).name
    router._replicas[cold_pick].load = 2
    assert router.pick(cold).name != cold_pick


# -- engine-to-engine sharing -------------------------------------------------


@pytest.mark.perf
def test_engine_imports_published_blocks_stream_bit_identical():
    """The tentpole's tier-1 pin: engine B imports the full-block prefix
    engine A published and produces the BIT-IDENTICAL greedy stream an
    unshared engine produces — with import counters proving no prefill
    replaced the shipped blocks."""
    from tpu_task.serve.kvfleet import FleetKvClient

    cfg, params = _micro()
    tmp = tempfile.mkdtemp()
    backend = LocalBackend(tmp)
    client_a = FleetKvClient(backend, "ra", refresh_interval=0.0)
    engine_a = _engine(cfg, params, rng_seed=1, kv_client=client_a)
    prompt = np.asarray(list(range(1, 17)) + [20, 21], np.int32)
    rid_a = engine_a.submit(prompt, 8)
    out_a = engine_a.drain()[rid_a]
    assert client_a.publish(engine_a) > 0
    assert client_a.bytes_shipped > 0

    client_b = FleetKvClient(backend, "rb", refresh_interval=0.0)
    engine_b = _engine(cfg, params, rng_seed=2, kv_client=client_b)
    rid_b = engine_b.submit(prompt, 8)
    out_b = engine_b.drain()[rid_b]
    stats = engine_b.stats()["kvfleet"]
    assert stats["hit_blocks"] == 4               # 16 shared tokens / 4
    assert stats["import_requests"] == 1
    assert client_b.bytes_fetched > 0

    reference = _engine(cfg, params, rng_seed=3)
    rid_r = reference.submit(prompt, 8)
    assert out_b == reference.drain()[rid_r] == out_a
    # Re-admission of the same prefix hits LOCALLY now (adopted blocks
    # joined B's prefix cache) — the fleet is consulted once per prefix.
    rid_c = engine_b.submit(prompt, 8)
    engine_b.drain()
    assert engine_b.stats()["kvfleet"]["import_requests"] == 1


@pytest.mark.slow
def test_engine_import_int8_codes_and_sidecars_bit_identical():
    """Quantized block shipping: the int8 codes + scale sidecars another
    engine published import byte-faithfully — streams identical to an
    unshared int8 engine on the anchor config (the same exactness class
    as PR 9's int8 stream pin)."""
    from tpu_task.serve.kvfleet import FleetKvClient

    cfg, params = _micro()
    tmp = tempfile.mkdtemp()
    backend = LocalBackend(tmp)
    knobs = dict(block_size=8, n_blocks=32, max_len=48, kv_dtype="int8")
    client_a = FleetKvClient(backend, "ra", refresh_interval=0.0)
    engine_a = _engine(cfg, params, rng_seed=1, kv_client=client_a, **knobs)
    prompt = np.arange(1, 18, dtype=np.int32)
    rid_a = engine_a.submit(prompt, 6)
    out_a = engine_a.drain()[rid_a]
    client_a.publish(engine_a)

    client_b = FleetKvClient(backend, "rb", refresh_interval=0.0)
    engine_b = _engine(cfg, params, rng_seed=2, kv_client=client_b, **knobs)
    rid_b = engine_b.submit(prompt, 6)
    out_b = engine_b.drain()[rid_b]
    assert engine_b.stats()["kvfleet"]["hit_blocks"] == 2
    reference = _engine(cfg, params, rng_seed=3, **knobs)
    rid_r = reference.submit(prompt, 6)
    assert out_b == reference.drain()[rid_r] == out_a


# -- fleet-level legs (slow) --------------------------------------------------


def _fleet(tmp_path, *, replicas=1, seed=0, **spec_kwargs):
    from tpu_task.scheduler import CapacityPool, GangScheduler, TenantQuota
    from tpu_task.serve import (
        InProcessServeDriver,
        Router,
        ServeFleet,
        ServeSpec,
        wait_until,
    )

    driver = InProcessServeDriver(
        kv_backend=LocalBackend(str(tmp_path)))
    scheduler = GangScheduler(
        CapacityPool([32]), {"svc": TenantQuota(chips=32, weight=1.0)},
        driver)
    router = Router(seed=seed)
    spec = ServeSpec(service="chat", tenant="svc", replicas=replicas,
                     preset="micro", serving={"slots": 4}, **spec_kwargs)
    fleet = ServeFleet(scheduler, spec, router)
    # An untaught router learns the spec's engine block size at fleet
    # construction — affinity/depth chains stay aligned with what the
    # preset's engines actually cache (micro: block_size 4).
    assert router.block_size == 4
    fleet.launch()
    total = replicas + spec.prefill_replicas
    assert wait_until(lambda: len(fleet.refresh_endpoints()) == total,
                      60, tick=fleet.tick, period=0.05)
    fleet.tick()
    return driver, router, fleet


def _teardown(driver):
    for task_id in list(driver.running_ids()):
        driver._stop(task_id, graceful=False)


@pytest.mark.fleet
@pytest.mark.slow
def test_cold_replica_joining_mid_soak_hits_fleet_index(tmp_path):
    """The ISSUE acceptance leg: an 80%-shared-prefix workload runs, a
    new replica joins via the scheduler mid-soak, and its first
    shared-prefix request imports from the fleet index instead of
    re-prefilling — import counters prove it, and every stream is
    bit-identical to an unshared single engine's."""
    import jax.numpy as jnp

    from tpu_task.serve.replica import build_engine

    driver, router, fleet = _fleet(tmp_path, replicas=1)
    try:
        shared = list(range(1, 17))               # four full 4-token blocks
        prompts = [np.asarray(shared + [30 + i, 31 + i], np.int32)
                   if i % 5 else RNG.integers(0, 64, size=10)
                   for i in range(10)]
        fids = [router.submit(p, 6) for p in prompts]
        router.drain(deadline_s=120, on_idle=fleet.tick)

        # Mid-soak membership change: scale to 2 via the scheduler, then
        # retire the warm replica so the cold one must serve.
        fleet.scale_to(2)
        assert fleet.live_replicas() == 2
        from tpu_task.serve import wait_until
        assert wait_until(
            lambda: len(fleet.refresh_endpoints()) == 2, 60,
            tick=fleet.tick, period=0.05)
        warm = "chat-r0"
        driver.kill(warm, graceful=True)
        fleet.tick()
        cold_name = next(tid for tid in driver.running_ids())
        cold = driver._servers[cold_name]
        assert cold.engine.stats()["kvfleet"]["hit_blocks"] == 0

        fid = router.submit(np.asarray(shared + [99, 98], np.int32), 6)
        out = router.drain(deadline_s=120, on_idle=fleet.tick)
        stats = cold.engine.stats()["kvfleet"]
        assert stats["hit_blocks"] > 0            # imported, not prefilled
        assert stats["import_requests"] >= 1

        # Bit-identity of EVERY stream vs one unshared engine fed the
        # router-derived keys.
        engine = build_engine("micro")
        for f in [*fids, fid]:
            request = router.request(f)
            rid = engine.submit(
                request.prompt, request.max_new_tokens,
                key=jnp.asarray(np.asarray(request.key, np.uint32)))
            assert engine.drain()[rid] == out[f]
    finally:
        _teardown(driver)


@pytest.mark.fleet
@pytest.mark.slow
def test_prefill_decode_split_hands_off_at_boundary_token(tmp_path):
    """Disaggregated prefill/decode: a long prompt takes the prefill
    pool first (role-dispatched), hands off at the boundary token, and
    the decode replica resumes by IMPORTING the published blocks — the
    stream stays bit-identical to an unshared engine, and the dispatch
    spans record the split (role + cached-prefix depth)."""
    import jax.numpy as jnp

    from tpu_task.serve.replica import build_engine

    driver, router, fleet = _fleet(
        tmp_path, replicas=1, prefill_replicas=1,
        prefill_serving={"chunk_tokens": 24}, prefill_threshold=16)
    try:
        assert router.prefill_threshold == 16     # spec taught the router
        roles = {name: r["role"] for name, r in router.replicas().items()}
        assert sorted(roles.values()) == ["decode", "prefill"]

        long_prompt = np.arange(1, 25, dtype=np.int32)
        fid = router.submit(long_prompt, 8)
        out = router.drain(deadline_s=120, on_idle=fleet.tick)
        assert router.handoffs == 1

        request = router.request(fid)
        engine = build_engine("micro")
        rid = engine.submit(
            request.prompt, request.max_new_tokens,
            key=jnp.asarray(np.asarray(request.key, np.uint32)))
        assert engine.drain()[rid] == out[fid]

        decode = driver._servers["chat-r0"]
        assert decode.engine.stats()["kvfleet"]["hit_blocks"] > 0
        prefill = driver._servers["chat-p0"]
        assert prefill.engine.stats()["kvfleet"]["published_blocks"] > 0

        spans = [s for s in router.obs.tracer.finished()
                 if s.name == "dispatch" and s.attrs.get("fid") == fid]
        assert [s.attrs["role"] for s in spans] == ["prefill", "decode"]
        assert spans[0].status == "prefilled"
        assert "cached_depth" in spans[0].attrs
        # A short prompt never takes the prefill leg.
        fid2 = router.submit(np.arange(1, 9, dtype=np.int32), 4)
        router.drain(deadline_s=120, on_idle=fleet.tick)
        assert router.request(fid2).dispatches == 1
        assert router.handoffs == 1
    finally:
        _teardown(driver)


# -- prefetch-ahead imports (router next-turn hints) --------------------------


def test_engine_prefetch_chain_warms_cache_before_any_request():
    """The prefetch-ahead satellite, engine half: ``prefetch_chain``
    pulls a published chain into the LOCAL prefix cache with no request
    in sight — counted under ``kvfleet.prefetch_blocks`` — so the
    session's next turn admits on local hits (the fleet is not even
    consulted) and streams bit-identically."""
    from tpu_task.ml.serving.cache import chain_block_hashes
    from tpu_task.serve.kvfleet import FleetKvClient

    cfg, params = _micro()
    tmp = tempfile.mkdtemp()
    backend = LocalBackend(tmp)
    client_a = FleetKvClient(backend, "ra", refresh_interval=0.0)
    engine_a = _engine(cfg, params, rng_seed=1, kv_client=client_a)
    prompt = np.asarray(list(range(1, 17)), np.int32)
    rid_a = engine_a.submit(prompt, 8)
    out_a = engine_a.drain()[rid_a]
    assert client_a.publish(engine_a) > 0

    # The hint: the next turn's context extends prompt + out_a — its
    # full-block chain is knowable now and already published above.
    session_ids = np.concatenate([prompt, np.asarray(out_a, np.int32)])
    hashes = chain_block_hashes(session_ids, 4)

    client_b = FleetKvClient(backend, "rb", refresh_interval=0.0)
    engine_b = _engine(cfg, params, rng_seed=2, kv_client=client_b)
    imported = engine_b.prefetch_chain(hashes)
    # The stream's LAST token is emitted but never written back (decode
    # stops), so its block holds one fewer valid position than the id
    # chain implies: every published block imports, the tail one misses.
    assert imported == len(hashes) - 1
    stats = engine_b.stats()["kvfleet"]
    assert stats["prefetch_blocks"] == imported
    assert engine_b.allocator.referenced == 0     # cached at ref 0
    # Idempotent: a second hint for the same chain imports nothing.
    assert engine_b.prefetch_chain(hashes) == 0

    # Next turn: the extended prompt admits on LOCAL hits — zero new
    # fleet imports on the TTFT path — and streams bit-identically.
    turn2 = np.concatenate([session_ids, np.asarray([30, 31], np.int32)])
    rid_b = engine_b.submit(turn2, 6)
    out_b = engine_b.drain()[rid_b]
    after = engine_b.stats()["kvfleet"]
    assert after["import_requests"] == 0
    assert engine_b.stats()["prefix_cache"]["blocks_saved"] >= imported
    reference = _engine(cfg, params, rng_seed=3)
    rid_r = reference.submit(turn2, 6)
    assert out_b == reference.drain()[rid_r]


def test_router_hints_next_turn_pick_on_completion():
    """The router half: with ``prefetch_next_turn`` on, a completed
    request fires ONE ``POST /prefetch`` at the replica the session's
    next turn would land on; when the serving replica drains away, the
    hint warms the SIBLING (counted on both sides), and the next turn
    served there needs no admission-path fleet import."""
    from tpu_task.serve import ReplicaServer, Router, wait_until
    from tpu_task.serve.kvfleet import FleetKvClient

    tmp = tempfile.mkdtemp()
    backend = LocalBackend(tmp)
    servers = [
        ReplicaServer(preset="micro",
                      kv_client=FleetKvClient(backend, f"r{i}",
                                              refresh_interval=0.0),
                      kv_publish_every=1).start()
        for i in range(2)]
    try:
        router = Router(seed=0, prefetch_next_turn=True, block_size=4)
        router.set_replicas({
            f"r{i}": {"url": server.url, "boot_id": server.boot_id}
            for i, server in enumerate(servers)})
        prompt = list(range(1, 17))
        fid = router.submit(prompt, 8)
        out = router.drain(deadline_s=60)[fid]
        server_by_name = {f"r{i}": s for i, s in enumerate(servers)}
        serving = server_by_name[router.request(fid).replica]
        sibling = next(s for s in servers if s is not serving)
        # Wait until the SIBLING can see the next-turn chain's head —
        # not just any published block: publishes go hottest-first, so
        # under a starved host a published_blocks>0 wait can observe a
        # mid-beat state whose advertised blocks miss the chain head,
        # and the (one-shot) hint below, importing leading-consecutive
        # only, would pull 0. lookup_chain is the hint handler's own
        # precondition (refresh + consecutive depth, no import
        # counters). Then fail the serving replica out of membership:
        # the session's next turn must land elsewhere.
        chain = router._chain_hashes(prompt + out)
        assert wait_until(
            lambda: sibling.kv_client.lookup_chain(chain) >= 1, 10)
        # pump's DONE arm already fired one hint automatically (then
        # targeting the warm serving replica — a no-op import).
        auto_hints = router.prefetch_hints
        assert auto_hints >= 1
        router._replicas[router.request(fid).replica].healthy = False
        router._hint_next_turn(router.request(fid))
        assert router.prefetch_hints == auto_hints + 1
        assert sibling.engine.stats()["kvfleet"]["prefetch_blocks"] > 0

        turn2 = prompt + out + [30, 31]
        fid2 = router.submit(turn2, 4)
        out2 = router.drain(deadline_s=60)[fid2]
        assert router.request(fid2).replica != router.request(fid).replica
        assert len(out2) == 4
        # The prefetched blocks served the admission locally: no fleet
        # import landed on the next turn's TTFT path.
        assert sibling.engine.stats()["kvfleet"]["import_requests"] == 0
        assert sibling.engine.stats()["prefix_cache"]["blocks_saved"] > 0
    finally:
        for server in servers:
            server.stop()
