"""Repo lint (``tpu_task.tools.repo_lint``): the live tree stays clean,
and the two rules actually catch their seeded violations.

Rule 1: no ``jnp.concatenate`` in serving token paths (jax 0.4.x CPU
SPMD miscompile under shard_map, PR 15). Rule 2: no blocking device
reads inside the engine's marked overlapped-dispatch region (PR 16) —
and deleting the markers is itself a finding, so the check cannot be
silently disabled.
"""

import textwrap

from tpu_task.tools import repo_lint


def test_repo_is_clean():
    assert repo_lint.run() == []


def test_concatenate_flagged_without_allow_comment():
    text = textwrap.dedent("""\
        import jax.numpy as jnp
        def pack(a, b):
            return jnp.concatenate([a, b], axis=0)
    """)
    findings = repo_lint.lint_concatenate_text(text, "fake/model.py")
    assert len(findings) == 1
    assert findings[0].startswith("fake/model.py:3:")
    assert "shard_map" in findings[0]


def test_concatenate_allow_comment_opts_out():
    text = ("host_ids = jnp.concatenate(parts)"
            "  # lint: allow-concatenate (host-side)\n")
    assert repo_lint.lint_concatenate_text(text, "fake/model.py") == []


def test_jnp_asarray_never_trips_blocking_rule():
    # jnp.asarray is the sanctioned host->device staging call; only a
    # bare np.asarray (a device read) may be flagged inside the region.
    text = textwrap.dedent("""\
        # lint: begin-overlap-dispatch
        x = jnp.asarray(tokens)
        # lint: end-overlap-dispatch
    """)
    assert repo_lint.lint_overlap_text(text, "fake/engine.py") == []


def test_blocking_reads_flagged_inside_region_only():
    text = textwrap.dedent("""\
        ys = np.asarray(record["ys"])      # before region: fine
        # lint: begin-overlap-dispatch
        jax.block_until_ready(ys)
        host = np.asarray(device_value)
        got = jax.device_get(device_value)
        # lint: end-overlap-dispatch
        tail = np.asarray(record["ys"])    # after region: fine
    """)
    findings = repo_lint.lint_overlap_text(text, "fake/engine.py")
    assert len(findings) == 3
    assert [f.split(":")[1] for f in findings] == ["3", "4", "5"]
    assert all("overlapped" in f for f in findings)


def test_missing_markers_is_a_finding():
    findings = repo_lint.lint_overlap_text("x = 1\n", "fake/engine.py")
    assert len(findings) == 1
    assert "not found" in findings[0]


def test_unterminated_begin_marker_is_a_finding():
    text = textwrap.dedent("""\
        # lint: begin-overlap-dispatch
        x = 1
        # lint: end-overlap-dispatch
        # lint: begin-overlap-dispatch
        y = 2
    """)
    findings = repo_lint.lint_overlap_text(text, "fake/engine.py")
    assert any("unterminated" in f for f in findings)


def test_tier_migrate_blocking_reads_flagged():
    # Rule 3 (the demote/promote staging region) rides the same
    # discipline: a synchronous device read inside the markers is a
    # finding, and deleting the markers is itself a finding.
    text = textwrap.dedent("""\
        # lint: begin-tier-migrate
        staged = stage_block_arrays(self.pools, block)
        payload = np.asarray(staged[0]["k"])
        # lint: end-tier-migrate
        forced = np.asarray(staged)            # consume edge: fine
    """)
    findings = repo_lint.lint_tier_text(text, "fake/engine.py")
    assert len(findings) == 1
    assert findings[0].startswith("fake/engine.py:3:")
    assert "tier-migrate" in findings[0]


def test_tier_migrate_missing_markers_is_a_finding():
    findings = repo_lint.lint_tier_text("x = 1\n", "fake/engine.py")
    assert len(findings) == 1
    assert "tier-migrate" in findings[0] and "not found" in findings[0]
