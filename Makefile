# tpu-task build/test entry points.
# Role of /root/reference/Makefile:13-47 (build/install/test/smoke/sweep),
# re-shaped for a Python package: the "binary" is the wheel the worker
# bootstrap installs (machine/wheel.py stages it into the task bucket).

PYTHON ?= python3

# Seed for the chaos soak: any run is replayable by pinning this.
TPU_TASK_CHAOS_SEED ?= 20260804

.PHONY: test lint smoke sweep bench bench-steady bench-serving bench-sched bench-decode bench-fleet bench-fleetkv bench-obs bench-goodput bench-tier bench-sla bench-lora sched sched-soak chaos fleet kvfleet tiering moe moe-serve serve-soak sla-soak lora obs watch wheel multichip kernels-tpu clean

# Hermetic suite (the reference's `make test`, 30 s budget there; ours spans
# the fake control planes, sharded-compute CPU checks, and the loopback GCS
# integration, so the budget is minutes, not seconds).
test:
	$(PYTHON) -m pytest tests/ -q

# Repo lint (runs in tier-1 via tests/test_repo_lint.py): flags
# jnp.concatenate feeding shard_map token paths (the jax 0.4.x CPU SPMD
# miscompile, PR 15) and blocking calls inside the engine's overlapped
# dispatch region (PR 16) — the two invariants a refactor silently breaks.
lint:
	$(PYTHON) -m tpu_task.tools.repo_lint

# Real-cloud smoke: full lifecycle with double-invoke idempotency, gated per
# provider (`make smoke` equivalent; 30 min budget — Makefile:42-44).
# Usage: SMOKE_TEST_ENABLE_TPU=1 GOOGLE_APPLICATION_CREDENTIALS_DATA=... make smoke
smoke:
	$(PYTHON) -m pytest tests/test_smoke_real.py -m smoke -q

# Delete stray smoke-test resources (the always-run sweep job, smoke.yml:96-101).
sweep:
	SMOKE_TEST_SWEEP=1 $(PYTHON) -m pytest tests/test_smoke_real.py -m smoke -q

# Headline benchmark: one JSON line (driver contract). The extra section
# carries every subsystem's cost model, including the gang scheduler
# (`scheduler`: queue-latency p50/p99, utilization, requeue fairness —
# standalone via `make bench-sched` / `bench.py scheduler`).
bench:
	$(PYTHON) bench.py

# Steady-state cost model only: requests/tick + bytes/tick for a no-change
# sync tick and an unchanged 32-machine poll, before/after the manifest
# planner + conditional poll cache (loopback GCS emulator counters).
bench-steady:
	$(PYTHON) bench.py steady_state

# Serving cost model only: continuous-batching engine (paged KV cache) vs
# batch-static generate on one mixed-length Poisson workload — throughput,
# TTFT percentiles, KV high-water vs the dense worst case — plus the three
# production-traffic scenarios (shared-prefix workload through the
# refcounted prefix cache, long-prompt-under-load through chunked prefill,
# speculative accept-rate sweep); all run on CPU.
bench-serving:
	$(PYTHON) bench.py serving

# Tiered-KV bench legs only (PR 17): the serving section's `tiering`
# subsection — resume latency per residency tier (HBM hit vs host
# promote vs recompute, greedy streams asserted identical — EXITS
# NONZERO on divergence), idle-session capacity with/without the host
# rung, the batch-32 overlap leg (host_gap_frac ~0 while blocks demote
# in the covered window), and the int4-over-int8 density ratio (~2× the
# blocks at the same HBM budget; full dtype table in the serving
# section's kv_density).
bench-tier:
	$(PYTHON) bench.py serving --tier-only

# Gang-scheduler cost model only: queue-latency percentiles, pool
# utilization, and per-tenant requeue fairness under Poisson arrivals on
# the virtual clock (pure model; milliseconds per hundred tasks).
bench-sched:
	$(PYTHON) bench.py scheduler

# Multi-tenant LoRA bench legs only (PR 19): the serving section's
# `adapters` subsection — adapters-per-replica density sweep (tok/s at
# 0/25/100% adapter-bearing slots, adapter-less overhead ratio), and the
# live weight-roll latency. Asserts every mixed-batch stream bit-matches
# a dedicated single-adapter engine — EXITS NONZERO on divergence.
bench-lora:
	$(PYTHON) bench.py serving --lora-only

# Paged-decode kernel grid only: impl (xla gather vs Pallas kernel vs the
# DMA-pipelined kernel) × kv_dtype (model dtype vs int8) × batch {1,8,32}
# — decode ms/token and KV bytes/token — plus the pipelined-vs-PR9
# head-to-head on the long fragmented table. EXITS NONZERO if the
# pipelined kernel regresses there (wall-clock on TPU; kernel parity
# everywhere — interpreter wall is emulation tax, not kernel speed). The
# tier-1 interpret-mode parity/smoke suite is tests/test_paged_attention.py.
# The second line runs the async-engine legs (PR 16): sync vs overlapped
# loop A/B (greedy bit-identity asserted — exits nonzero on divergence)
# and the admission-burst p99-TTFT scenario (prefill_slots 1 vs burst).
# On TPU the grid also records a compiled pipelined-kernel profiler
# capture under profiles/decode_pipelined.
bench-decode:
	$(PYTHON) bench.py generation --decode-kernel
	$(PYTHON) bench.py goodput --async-only

# Fleet-serving cost model only: aggregate tok/s + TTFT percentiles vs
# replica count {1,2,4} through the WHOLE serve subsystem (scheduler-
# admitted replica gangs, session-affine router, loopback HTTP), plus the
# preempt-one-replica leg (failover + capacity-restore times). CPU note:
# replicas share one host's cores, so throughput does not scale like
# chips — the tracked signals are queue wait and the recovery legs.
bench-fleet:
	$(PYTHON) bench.py fleet

# SLA brownout curve (PR 18): premium + best_effort attainment vs load at
# 1x/2x/4x the calibrated service rate; nonzero exit if best_effort
# attainment ever exceeds premium's (protection inverted).
bench-sla:
	$(PYTHON) bench.py fleet --overload

# Tier-1-speed gang-scheduler tests: queue/quota/pool model, fair-share
# ordering, victim-order properties, CLI, bench smoke (all virtual-time).
sched:
	$(PYTHON) -m pytest tests/ -m "scheduler and not slow" -q

# Fleet-scale soak: the 1000-task multi-tenant chaos soak (3 seeded
# preemption waves + durable-queue restart, virtual clock) plus the
# real-task integration test where a scheduler preemption rides the PR 3
# requeue governor of live fake-mode agents. Replayable from the seed.
sched-soak:
	TPU_TASK_CHAOS_SEED=$(TPU_TASK_CHAOS_SEED) \
		$(PYTHON) -m pytest tests/ -m "scheduler and slow" -q

# Seeded fault-injection soak: preemptions + a hung worker + flaky storage
# against the hermetic TPU control plane, replayable from the seed.
chaos:
	TPU_TASK_CHAOS_SEED=$(TPU_TASK_CHAOS_SEED) \
		$(PYTHON) -m pytest tests/ -m chaos -q

# Fleet-serving tests (serve as a first-class task): replica front end,
# session-affine router, re-dispatch under chaos transport, autoscale,
# serve gangs through the scheduler — all in-process loopback HTTP.
fleet:
	$(PYTHON) -m pytest tests/ -m fleet -q

# Fleet-wide KV plane tests: block export/import bit-faithfulness, the
# delta-synced bucket index, block-aligned affinity, cross-engine import
# stream identity, and (slow subset) the cold-replica-joins-mid-soak and
# prefill/decode-split handoff legs.
kvfleet:
	$(PYTHON) -m pytest tests/ -m kvfleet -q

# Tiered-KV hierarchy tests (PR 17): int4 pack/unpack error property +
# density, demote→promote byte identity across every kv dtype, the
# 5×-HBM session soak (sync and overlapped loops, streams bit-identical
# to an all-HBM reference), the long-context int4 leg, the
# preemption-while-demoted regression, host-budget spill into the fleet
# bucket, and prefetch_chain host→HBM promotion. Two smoke pins run in
# tier-1; the soaks are slow.
tiering:
	$(PYTHON) -m pytest tests/ -m tiering -q

# Multi-tenant serving tests: paged LoRA adapters in the one fused step
# (mixed-batch bit-identity vs dedicated engines, scratch-block no-op
# exactness, LRU evict + bucket reload) and the drain-free weight
# hot-swap (generation pinning, export/resume round-trip, the replica
# roll soak in the slow subset).
lora:
	$(PYTHON) -m pytest tests/ -m lora -q

# Sharded-replica / MoE serving tests: ep all_to_all dispatch identity,
# tp×ep gang engines, sharded spec decode, scheduler chip accounting,
# the fleet serving a MoE config too big for one chip (slow subset runs
# the full tp×ep matrix and the fleet legs).
moe:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -m moe -q

# Sharded-replica MoE serving grid: engine tok/s + per-shard KV MB (÷tp)
# + per-shard expert-weight MB (÷ep) at tp {1,8} × ep {1,4} on a forced
# 32-device host platform. EXITS NONZERO if greedy streams diverge
# anywhere on the grid (the docs/parity.md token-identity contract).
moe-serve:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py fleet --moe-only

# Fleet-KV bench legs only: shared_prefix_scaling (aggregate tok/s +
# re-prefill chunk work at replicas {1,2,4}, fleet-KV on vs off,
# 80%-shared-prefix workload) and prefill_decode_split (inter-token
# latency + long TTFT of running streams under sustained long-prompt
# load: 1 prefill + 2 decode vs 3 unified replicas at both unified
# chunk budgets; decode_pool_chunk_rows pins the moved interference —
# the wall-clock p99 win is hardware-gated). Same CPU shared-cores
# caveat as bench-fleet.
bench-fleetkv:
	$(PYTHON) bench.py fleet --kvfleet-only

# Serve-as-a-task chaos soak: replica gangs as REAL fake-mode TPU tasks,
# a seeded mid-stream replica preemption (SIGTERM → drain → export →
# requeue through the PR 3 governor), router failover to the sibling,
# greedy streams pinned bit-identical to an unpreempted run. Replayable
# from the seed.
serve-soak:
	TPU_TASK_CHAOS_SEED=$(TPU_TASK_CHAOS_SEED) \
		$(PYTHON) -m pytest tests/ -m "fleet and slow" -q

# SLA brownout soak (PR 18): seeded 2x-overload + preemption wave; premium
# p99 TTFT must hold while best_effort sheds, fairness invariants intact.
sla-soak:
	TPU_TASK_CHAOS_SEED=$(TPU_TASK_CHAOS_SEED) \
		$(PYTHON) -m pytest tests/ -m "sla and slow" -q

# Observability-plane tests (tier-1 speed): metrics registry + histogram
# math (the shared-quantile pin against numpy), tracer/ring/header, span
# export + chrome-trace validity, engine spans with the obs-off
# zero-overhead path, scheduler queue-latency surfacing, obs CLI.
obs:
	$(PYTHON) -m pytest tests/ -m "obs and not slow" -q

# Observability overhead leg: engine tok/s with tracing/metrics on vs off
# (adjacent-pair median — the <= 5% contract; obs off is a code-path
# guard, so that leg pays exactly zero).
bench-obs:
	$(PYTHON) bench.py obs

# Goodput/MFU/dispatch-overhead leg: in-program vs host-gap wall split
# (the ROADMAP-4 "dispatches dominate" gauge), goodput ratio, and the
# static-FLOP-model MFU gauge at batch {1,8,32}, cross-checked against
# XLA cost_analysis where the backend provides one. Includes the
# micro_k ∈ {1,4,8} dispatch-amortization sweep at batch 32 (greedy
# streams asserted bit-identical across K — exits nonzero on
# divergence; dispatches/token and host_gap_frac per K). Pass --async
# for the sync-vs-overlapped A/B + admission-burst legs as well.
bench-goodput:
	$(PYTHON) bench.py goodput --async

# One-shot `obs watch` frame against the default state root — the render
# smoke for the live dashboard (tok/s, goodput, MFU, queue depth, QLAT,
# burn-rate alerts). Run the real thing without --once.
watch:
	$(PYTHON) -m tpu_task.cli.main obs watch --once

# Build the agent wheel the worker bootstrap installs.
wheel:
	$(PYTHON) -m pip wheel --no-deps --no-build-isolation -w dist .

# Compile-check the multi-chip sharded train step on a virtual 8-device
# mesh, then the tensor-parallel serving points (engine tok/s + KV
# bytes/shard at tp 1 and 8) — MULTICHIP captures cover serve AND train.
multichip:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PYTHON) __graft_entry__.py
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PYTHON) bench.py serving --tp 1,8

# Compiled-path correctness on an attached real TPU (not interpret mode):
# flash fwd+bwd + zigzag ring vs the XLA reference, fused cross-entropy,
# MoE routing, and the full train step, all at bf16 tolerance. Selects
# every test_compiled_* across the suite — the interpret-mode math tests
# are f32-exact and run in the hermetic suite on CPU.
kernels-tpu:
	TPU_TASK_TEST_REAL_TPU=1 $(PYTHON) -m pytest tests/ -k compiled -q

clean:
	rm -rf dist build *.egg-info ~/.tpu-task/wheels
