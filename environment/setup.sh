#!/bin/bash
# Baked-image provisioning for TPU-VM (and GCE) workers.
#
# Role of /root/reference/environment/setup.sh (docker + nvidia + terraform
# for the iterative-cml AMI), re-targeted: pre-install everything the
# tpu-task worker bootstrap would otherwise fetch at boot, so instances from
# the baked image skip the install stanzas entirely (the bootstrap's
# `command -v tpu-task` / `python3 -c 'import jax'` guards short-circuit)
# and cold-start in seconds.
#
# Usage (image pipeline — see environment/README.md):
#   1. boot a builder VM from the base image (TPU-VM: tpu-ubuntu2204-base)
#   2. copy the tpu-task wheel next to this script and run it
#   3. gcloud compute images create ... --source-disk=<builder-disk>
set -euo pipefail

export DEBIAN_FRONTEND=noninteractive

sudo apt-get update -qq
sudo apt-get install -y -qq python3-pip curl

# The tpu-task agent (data plane + self-destruct CLI). A wheel shipped next
# to this script wins; the package index is the fallback.
WHEEL="$(ls "$(dirname "$0")"/tpu_task-*.whl 2> /dev/null | head -1 || true)"
if test -n "$WHEEL"; then
  sudo python3 -m pip install --quiet "$WHEEL"
else
  sudo python3 -m pip install --quiet tpu-task
fi

# JAX for TPU (the libtpu wheel rides the jax[tpu] extra).
sudo python3 -m pip install --quiet 'jax[tpu]' \
  --find-links https://storage.googleapis.com/jax-releases/libtpu_releases.html

# Boot-time noise the bootstrap otherwise disables per-instance.
sudo systemctl disable --now apt-daily.timer apt-daily-upgrade.timer 2> /dev/null || true

echo "baked: $(tpu-task --help > /dev/null 2>&1 && echo tpu-task-ok) $(python3 -c 'import jax; print("jax", jax.__version__)')"
